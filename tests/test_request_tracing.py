"""Request-scoped distributed tracing, tail-latency attribution and
SLO burn-rate alerting (the serving-observability tentpole).

The acceptance end-to-end this file carries: one trace id produces a
complete cross-thread span tree for a ``/v1/predict`` and a streamed
``/v1/generate`` (both backends), per-phase attribution sums to the
whole-request latency within 5%, ``/metrics`` exposes exemplars on
the serving latency histograms, and an SLO burn-rate breach flips
``/healthz`` to degraded with the offending trace ids captured in a
flight-recorder bundle — plus the chaos leg: a worker crash-restart
where the surviving work keeps its original trace id.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import (MultiLayerNetwork,
                                NeuralNetConfiguration, chaos)
from deeplearning4j_tpu.nn.conf import updaters
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                               EmbeddingSequenceLayer,
                                               OutputLayer,
                                               RnnOutputLayer,
                                               TransformerEncoderLayer)
from deeplearning4j_tpu.observability import flight_recorder
from deeplearning4j_tpu.observability.registry import MetricsRegistry
from deeplearning4j_tpu.observability.slo import (SLO, BurnWindow,
                                                  SLOMonitor)
from deeplearning4j_tpu.observability.tracing import (RequestContext,
                                                      Sampler, Tracer,
                                                      current_context)
from deeplearning4j_tpu.serving import (BatchScheduler,
                                        CircuitBreaker,
                                        ContinuousBatcher,
                                        ModelRegistry, ModelServer,
                                        ServingMetrics)

pytestmark = pytest.mark.tracing

PREDICT_PHASES = ["admission", "queue_wait", "batch_form",
                  "device_step", "respond"]
GENERATE_PHASES = ["admission", "queue_wait", "prefill", "decode",
                   "respond"]


class EchoModel:
    """output = 2 * input, optional per-batch delay."""

    def __init__(self, delay=0.0):
        self.delay = delay

    def output(self, x):
        if self.delay:
            time.sleep(self.delay)
        return np.asarray(x) * 2.0


def _mlp(seed=0):
    conf = (NeuralNetConfiguration.builder().set_seed(seed)
            .updater(updaters.adam(0.01)).list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(4)).build())
    return MultiLayerNetwork(conf).init()


LM_V, LM_CAP = 13, 32


def _lm(seed=0):
    conf = (NeuralNetConfiguration.builder().set_seed(seed)
            .updater(updaters.adam(1e-3)).list()
            .layer(EmbeddingSequenceLayer(n_in=LM_V, n_out=16))
            .layer(TransformerEncoderLayer(n_heads=2, causal=True))
            .layer(RnnOutputLayer(n_out=LM_V, loss="mcxent"))
            .set_input_type(InputType.recurrent(LM_V, LM_CAP)).build())
    return MultiLayerNetwork(conf).init()


def _post(base, path, body, headers=None):
    req = urllib.request.Request(
        base + path, json.dumps(body).encode(),
        dict({"Content-Type": "application/json"}, **(headers or {})))
    try:
        with urllib.request.urlopen(req) as resp:
            return json.loads(resp.read()), resp.status, resp.headers
    except urllib.error.HTTPError as e:
        return json.loads(e.read()), e.code, e.headers


def _get(base, path):
    try:
        with urllib.request.urlopen(base + path) as resp:
            return json.loads(resp.read()), resp.status
    except urllib.error.HTTPError as e:
        return json.loads(e.read()), e.code


def _spans_for(tracer, trace_id, want_names, timeout=5.0):
    """Wait for (and return) the trace's spans: the root ``request``
    span lands AFTER the HTTP response is written, so readers poll."""
    deadline = time.monotonic() + timeout
    while True:
        spans = [e for e in tracer.events()
                 if e.get("trace_id") == trace_id]
        if want_names <= {s["name"] for s in spans}:
            return spans
        if time.monotonic() > deadline:
            raise AssertionError(
                f"trace {trace_id}: wanted {sorted(want_names)}, "
                f"got {sorted({s['name'] for s in spans})}")
        time.sleep(0.01)


def _trace_id_from(headers):
    tp = headers["traceparent"]
    ver, tid, span, flags = tp.split("-")
    assert ver == "00" and len(tid) == 32 and len(span) == 16
    return tid, span, flags


# ---------------------------------------------------------------------------
# head-based sampling
# ---------------------------------------------------------------------------

class TestSampler:
    def test_deterministic_in_trace_id(self):
        """Fleet consistency: every replica samples the SAME ids."""
        s1, s2 = Sampler(rate=0.25), Sampler(rate=0.25)
        ids = [RequestContext().trace_id for _ in range(200)]
        assert [s1.sample(t) for t in ids] == \
            [s2.sample(t) for t in ids]

    def test_rate_bounds(self):
        ids = [RequestContext().trace_id for _ in range(50)]
        assert not any(Sampler(rate=0.0).sample(t) for t in ids)
        assert all(Sampler(rate=1.0).sample(t) for t in ids)

    def test_rate_is_roughly_honoured(self):
        s = Sampler(rate=0.25)
        n = sum(s.sample(RequestContext().trace_id)
                for _ in range(2000))
        assert 0.15 < n / 2000 < 0.35

    def test_per_route_override(self):
        s = Sampler(rate=0.0, routes={"/v1/generate": 1.0})
        tid = RequestContext().trace_id
        assert not s.sample(tid, "/v1/predict")
        assert s.sample(tid, "/v1/generate")


# ---------------------------------------------------------------------------
# RequestContext: W3C header, attach, phase ledger
# ---------------------------------------------------------------------------

class TestRequestContext:
    def test_traceparent_round_trip(self):
        up = RequestContext(sampled=True, route="/v1/predict")
        hdr = up.traceparent()
        assert hdr == f"00-{up.trace_id}-{up.root_span_id}-01"
        down = RequestContext.from_traceparent(hdr, "/v1/predict")
        assert down.trace_id == up.trace_id         # identity kept
        assert down.parent_id == up.root_span_id    # correct linkage
        assert down.root_span_id != up.root_span_id
        assert down.sampled                         # flag honoured

    def test_malformed_headers_rejected(self):
        for bad in (None, "", "garbage", "00-xyz-abc-01",
                    "ff-" + "a" * 32 + "-" + "b" * 16 + "-01",
                    "00-" + "0" * 32 + "-" + "b" * 16 + "-01",
                    "00-" + "a" * 32 + "-" + "0" * 16 + "-01"):
            assert RequestContext.from_traceparent(
                bad, "/v1/predict") is None

    def test_unsampled_upstream_gets_own_head_decision(self):
        up = RequestContext(sampled=False)
        down = RequestContext.from_traceparent(
            up.traceparent(), "/v1/predict", Sampler(rate=1.0))
        assert down.sampled

    def test_attach_restores_previous_context(self):
        outer, inner = RequestContext(), RequestContext()
        assert current_context() is None
        with outer.attach():
            assert current_context() is outer
            with inner.attach():
                assert current_context() is inner
            assert current_context() is outer    # no leakage
        assert current_context() is None

    def test_phase_ledger_sums_to_total(self):
        """Phases are contiguous segments: the ledger reconciles
        against the whole-request wall time by construction."""
        ctx = RequestContext(sampled=False)
        ctx.phase_done("admission", now_in="queue_wait")
        time.sleep(0.01)
        ctx.phase_done("queue_wait", now_in="device_step")
        ctx.phase_done("device_step")
        total = ctx.finish()
        assert ctx.phases["queue_wait"] >= 0.01
        assert sum(ctx.phases.values()) == pytest.approx(
            total, rel=1e-6)

    def test_error_promotes_to_sampled(self):
        tr = Tracer(enabled=False)
        ctx = RequestContext(sampled=False, route="/v1/predict",
                             tracer=tr)
        ctx.set_error(ValueError("boom"))
        assert ctx.sampled
        ctx.finish()
        roots = [e for e in tr.events() if e["name"] == "request"]
        assert len(roots) == 1
        assert "boom" in roots[0]["args"]["error"]

    def test_finish_idempotent_and_unsampled_emits_nothing(self):
        tr = Tracer(enabled=False)
        ctx = RequestContext(sampled=False, tracer=tr)
        t1 = ctx.finish()
        assert ctx.finish() == t1
        assert tr.events() == []

    def test_to_debug_shape(self):
        ctx = RequestContext(sampled=True, route="/v1/predict",
                             deadline=time.monotonic() + 5.0)
        ctx.phase_done("admission", now_in="queue_wait")
        d = ctx.to_debug()
        assert d["trace_id"] == ctx.trace_id
        assert d["phase"] == "queue_wait"
        assert d["age_ms"] >= 0
        assert 0 < d["deadline_remaining_ms"] <= 5000
        assert "admission" in d["phases_ms"]


# ---------------------------------------------------------------------------
# acceptance e2e: cross-thread span trees over HTTP, both backends
# ---------------------------------------------------------------------------

class TestTraceContinuityHTTP:
    @pytest.fixture()
    def served(self):
        tracer = Tracer(enabled=False)   # request spans bypass enable
        reg = ModelRegistry()
        reg.register("iris", _mlp())
        reg.register("lm", _lm())
        srv = ModelServer(reg, port=0, slots=2, capacity=LM_CAP,
                          wait_ms=2.0, sample_rate=1.0, slow_ms=0.0,
                          tracer=tracer).start()
        yield srv, tracer, f"http://127.0.0.1:{srv.port}"
        srv.stop(drain=True, timeout=10.0)

    def test_predict_yields_complete_cross_thread_span_tree(
            self, served):
        srv, tracer, base = served
        body, code, headers = _post(
            base, "/v1/predict",
            {"model": "iris", "inputs": [[0.1, 0.2, 0.3, 0.4]]})
        assert code == 200
        tid, root_span, flags = _trace_id_from(headers)
        assert flags == "01"                      # sampled, and says so
        spans = _spans_for(tracer, tid,
                           set(PREDICT_PHASES) | {"request"})
        by_name = {s["name"]: s for s in spans}
        root = by_name["request"]
        assert root["span_id"] == root_span
        assert "parent_id" not in root            # tree root
        for phase in PREDICT_PHASES:
            assert by_name[phase]["parent_id"] == root["span_id"]
        # CROSS-THREAD: admission/respond stamp on the handler
        # thread, queue_wait/batch_form/device_step on the worker
        assert len({s["tid"] for s in spans}) >= 2
        assert root["args"]["route"] == "/v1/predict"
        assert root["args"]["model_version"] == 1
        assert root["args"]["http_status"] == 200

    def test_generate_stream_span_tree_and_streaming_histograms(
            self, served):
        srv, tracer, base = served
        body, code, headers = _post(
            base, "/v1/generate",
            {"model": "lm", "prompt": [1, 2, 3], "n_tokens": 4})
        assert code == 200 and len(body["ids"]) == 4
        tid, root_span, _ = _trace_id_from(headers)
        spans = _spans_for(tracer, tid,
                           set(GENERATE_PHASES) | {"request"})
        by_name = {s["name"]: s for s in spans}
        assert by_name["request"]["span_id"] == root_span
        for phase in GENERATE_PHASES:
            assert by_name[phase]["parent_id"] == root_span
        assert by_name["decode"]["args"]["tokens"] == 4
        assert len({s["tid"] for s in spans}) >= 2
        # TTFT / inter-token histograms, labeled by model version,
        # with the sampled trace id as an exemplar (exemplars are
        # OpenMetrics-only syntax)
        with urllib.request.urlopen(
                base + "/metrics?format=openmetrics") as resp:
            text = resp.read().decode()
        ttft_lines = [ln for ln in text.splitlines()
                      if ln.startswith("serving_ttft_seconds_bucket")
                      and 'endpoint="generate/lm/v1"' in ln
                      and 'model_version="1"' in ln]
        itl_lines = [ln for ln in text.splitlines()
                     if ln.startswith("serving_itl_seconds_bucket")
                     and 'endpoint="generate/lm/v1"' in ln
                     and 'model_version="1"' in ln]
        assert ttft_lines and itl_lines
        ttft = [ln for ln in ttft_lines
                if f'trace_id="{tid}"' in ln]
        assert ttft, "TTFT bucket lost its exemplar"

    def test_phase_attribution_reconciles_within_5pct(self, served):
        srv, tracer, base = served
        for _ in range(8):
            _post(base, "/v1/predict",
                  {"model": "iris", "inputs": [[1, 2, 3, 4]]})
        deadline = time.monotonic() + 5.0
        while True:       # recent entries land after the response
            dbg, _ = _get(base, "/debug/requests")
            if len(dbg["recent"]) >= 8 or time.monotonic() > deadline:
                break
            time.sleep(0.01)
        recent = dbg["recent"]
        assert len(recent) >= 8
        for entry in recent:
            phase_sum = sum(entry["phases_ms"].values())
            assert phase_sum == pytest.approx(
                entry["duration_ms"], rel=0.05), entry
        # the aggregate report agrees: per-endpoint decomposition
        # accounts for the request's wall time and names a culprit
        att = dbg["latency_attribution"]["predict/iris/v1"]
        assert att["count"] >= 8
        assert set(att["phases_ms"]) >= set(PREDICT_PHASES)
        assert att["phase_sum_over_total"] == pytest.approx(
            1.0, abs=0.25)
        assert att["dominant_phase"]["p99"] in att["phases_ms"]

    def test_metrics_expose_latency_exemplars(self, served):
        srv, tracer, base = served
        _, _, headers = _post(
            base, "/v1/predict",
            {"model": "iris", "inputs": [[1, 2, 3, 4]]})
        tid, _, _ = _trace_id_from(headers)
        with urllib.request.urlopen(
                base + "/metrics?format=openmetrics") as resp:
            ctype = resp.headers["Content-Type"]
            text = resp.read().decode()
        assert "application/openmetrics-text" in ctype
        assert text.rstrip().endswith("# EOF")
        hits = [ln for ln in text.splitlines()
                if ln.startswith("serving_latency_seconds_bucket")
                and "# {" in ln and 'trace_id="' in ln]
        assert hits, "no exemplar on the serving latency histogram"
        # the classic text format must NOT carry exemplars — they are
        # a parse error that would kill a whole 0.0.4 scrape
        with urllib.request.urlopen(
                base + "/metrics?format=prometheus") as resp:
            classic = resp.read().decode()
        assert "# {" not in classic and "# EOF" not in classic

    def test_router_hop_adopts_upstream_trace(self, served):
        """A router→replica hop keeps the request's identity: the
        replica's whole span tree lives under the caller's trace id,
        parented to the caller's span."""
        srv, tracer, base = served
        upstream = RequestContext(sampled=True, route="/v1/predict")
        body, code, headers = _post(
            base, "/v1/predict",
            {"model": "iris", "inputs": [[1, 2, 3, 4]]},
            headers={"traceparent": upstream.traceparent()})
        assert code == 200
        tid, root_span, _ = _trace_id_from(headers)
        assert tid == upstream.trace_id
        spans = _spans_for(tracer, upstream.trace_id,
                           set(PREDICT_PHASES) | {"request"})
        root = {s["name"]: s for s in spans}["request"]
        assert root["parent_id"] == upstream.root_span_id
        assert root["span_id"] == root_span

    def test_debug_slots_and_traces_endpoints(self, served):
        srv, tracer, base = served
        _post(base, "/v1/generate",
              {"model": "lm", "prompt": [1, 2], "n_tokens": 3})
        dbg, code = _get(base, "/debug/slots")
        assert code == 200
        slots = dbg["backends"]["generate/lm/v1"]["slots"]
        assert len(slots) == 2
        assert all(s["state"] in ("free", "prefill", "decode")
                   for s in slots)
        dbg, code = _get(base, "/debug/traces")
        assert code == 200 and dbg["sample_rate"] == 1.0
        # slow_ms=0 ⇒ every completed request is a "slow" trace
        deadline = time.monotonic() + 5.0
        while not dbg["slow"] and time.monotonic() < deadline:
            time.sleep(0.01)
            dbg, _ = _get(base, "/debug/traces")
        assert dbg["slow"] and dbg["slow"][-1]["trace_id"]

    def test_in_flight_request_visible_with_current_phase(self):
        reg = ModelRegistry()
        reg.register("echo", EchoModel(delay=0.4))
        tracer = Tracer(enabled=False)
        srv = ModelServer(reg, port=0, wait_ms=1.0, sample_rate=1.0,
                          tracer=tracer).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            t = threading.Thread(
                target=_post, args=(base, "/v1/predict",
                                    {"model": "echo",
                                     "inputs": [[1.0, 2.0]]}))
            t.start()
            seen = None
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                dbg, _ = _get(base, "/debug/requests")
                if dbg["in_flight"]:
                    seen = dbg["in_flight"][0]
                    if seen["phase"] == "device_step":
                        break
                time.sleep(0.02)
            t.join()
            assert seen is not None
            assert seen["trace_id"] and seen["age_ms"] >= 0
            assert seen["phase"] in ("queue_wait", "batch_form",
                                     "device_step", "respond")
        finally:
            srv.stop(drain=True, timeout=10.0)


# ---------------------------------------------------------------------------
# sampling gates emission; errors are always sampled
# ---------------------------------------------------------------------------

class TestSamplingGates:
    @pytest.fixture()
    def unsampled(self):
        tracer = Tracer(enabled=False)
        reg = ModelRegistry()
        reg.register("iris", _mlp())
        srv = ModelServer(reg, port=0, wait_ms=2.0, sample_rate=0.0,
                          tracer=tracer).start()
        yield srv, tracer, f"http://127.0.0.1:{srv.port}"
        srv.stop(drain=True, timeout=10.0)

    def test_unsampled_success_emits_no_spans(self, unsampled):
        srv, tracer, base = unsampled
        body, code, headers = _post(
            base, "/v1/predict",
            {"model": "iris", "inputs": [[1, 2, 3, 4]]})
        assert code == 200
        tid, _, flags = _trace_id_from(headers)
        assert flags == "00"
        time.sleep(0.1)
        assert [e for e in tracer.events()
                if e.get("trace_id") == tid] == []
        # but the attribution histograms recorded it anyway: phase
        # ledgers feed metrics at EVERY sampling rate
        att = srv.metrics.latency_attribution()["predict/iris/v1"]
        assert att["count"] == 1

    def test_errors_promote_to_sampled(self, unsampled):
        srv, tracer, base = unsampled
        body, code, headers = _post(
            base, "/v1/predict", {"model": "ghost",
                                  "inputs": [[1]]})
        assert code == 404
        assert body["trace_id"]            # error body names the trace
        tid, _, flags = _trace_id_from(headers)
        assert tid == body["trace_id"] and flags == "01"
        spans = _spans_for(tracer, tid, {"request"})
        root = {s["name"]: s for s in spans}["request"]
        assert "ghost" in root["args"]["error"]
        assert root["args"]["http_status"] == 404


# ---------------------------------------------------------------------------
# chaos: crash-restart keeps the original trace id
# ---------------------------------------------------------------------------

@pytest.mark.chaos
class TestCrashRestartContinuity:
    @pytest.fixture(autouse=True)
    def _clean_injector(self):
        yield
        chaos.uninstall()

    def test_batcher_pending_request_survives_with_trace_id(self):
        """A worker crash kills the stream mid-decode; the pending
        (admitted, unslotted) request is served by the RESTARTED
        worker loop — same trace id, complete span tree, spans
        stamped on both sides of the restart."""
        chaos.install({"faults": [{"site": "serving.worker.step",
                                   "kind": "crash", "at": [3]}]},
                      seed=1)
        tr = Tracer(enabled=False)
        cb = ContinuousBatcher(
            _lm(), slots=1, capacity=LM_CAP,
            breaker=CircuitBreaker(failure_threshold=5))
        try:
            first_ctx = RequestContext(sampled=True, route="gen",
                                       tracer=tr)
            second_ctx = RequestContext(sampled=True, route="gen",
                                        tracer=tr)
            first = cb.submit(np.array([1, 2, 3]), 4, ctx=first_ctx)
            second = cb.submit(np.array([4, 5]), 3, ctx=second_ctx)
            with pytest.raises(chaos.SimulatedCrashError):
                cb.wait(first)
            out = cb.wait(second)
            assert len(out) == 3
            second_ctx.finish()
            # original identity, end to end across the restart
            spans = _spans_for(tr, second_ctx.trace_id,
                               set(GENERATE_PHASES) | {"request"})
            assert {s["trace_id"] for s in spans} == \
                {second_ctx.trace_id}
            # the crashed stream is promoted to sampled: the casualty
            # leaves a trace naming the crash
            first_ctx.finish()
            root = {s["name"]: s for s in _spans_for(
                tr, first_ctx.trace_id, {"request"})}["request"]
            assert "SimulatedCrash" in root["args"]["error"]
        finally:
            assert cb.drain()

    def test_scheduler_crash_then_restart_full_tree(self):
        """The batch mid-device dies with the crash (its trace is
        promoted + error-stamped); the restarted worker serves the
        next request with a complete tree under its original id."""
        chaos.install({"faults": [{"site": "serving.worker.step",
                                   "kind": "crash", "at": [1]}]},
                      seed=1)
        tr = Tracer(enabled=False)
        s = BatchScheduler(EchoModel(), max_batch_size=4,
                           queue_limit=16, wait_ms=1.0,
                           breaker=CircuitBreaker(failure_threshold=3),
                           name="predict")
        try:
            dead_ctx = RequestContext(sampled=False, route="pred",
                                      tracer=tr)
            with pytest.raises(chaos.SimulatedCrashError):
                s.predict(np.ones((1, 4), np.float32), ctx=dead_ctx)
            assert dead_ctx.sampled          # crash promoted it
            ok_ctx = RequestContext(sampled=True, route="pred",
                                    tracer=tr)
            out = s.predict(np.full((1, 4), 2.0, np.float32),
                            ctx=ok_ctx)
            np.testing.assert_array_equal(out, np.full((1, 4), 4.0))
            ok_ctx.finish()
            spans = _spans_for(tr, ok_ctx.trace_id,
                               set(PREDICT_PHASES) | {"request"})
            assert {s_["trace_id"] for s_ in spans} == \
                {ok_ctx.trace_id}
        finally:
            s.shutdown()


# ---------------------------------------------------------------------------
# span-open sink delivery: unclosed spans reach the crash bundle
# ---------------------------------------------------------------------------

class TestUnclosedSpans:
    def test_sink_sees_open_then_close(self):
        tr = Tracer(enabled=True)
        got = []
        tr.add_sink(got.append)
        try:
            with tr.span("op"):
                opens = [e for e in got if e.get("ph") == "open"]
                assert [e["name"] for e in opens] == ["op"]
                assert opens[0]["span_id"]
            closes = [e for e in got if e.get("ph") != "open"]
            assert [e["name"] for e in closes] == ["op"]
            assert closes[0]["span_id"] == opens[0]["span_id"]
        finally:
            tr.remove_sink(got.append)

    def test_bundle_includes_unclosed_spans(self, tmp_path):
        """The post-mortem contract the satellite names: work still
        open at dump time rides events.jsonl with an ``unclosed``
        marker — and is retired once it closes."""
        tr = Tracer(enabled=True)
        rec = flight_recorder.FlightRecorder(
            out_dir=str(tmp_path), tracer=tr,
            registry=MetricsRegistry(), min_dump_interval_s=0.0)
        try:
            ctx = RequestContext(sampled=True, route="/v1/predict",
                                 tracer=tr)
            ctx.open_root()
            span = tr.span("device_step")
            span.__enter__()
            bundle = rec.dump(reason="crash", force=True)
            lines = [json.loads(ln) for ln in
                     open(os.path.join(bundle, "events.jsonl"))]
            unclosed = {e["name"]: e for e in lines
                        if e.get("unclosed")}
            assert set(unclosed) == {"request", "device_step"}
            assert unclosed["request"]["trace_id"] == ctx.trace_id
            assert unclosed["request"]["age_s"] >= 0
            manifest = json.load(
                open(os.path.join(bundle, "MANIFEST.json")))
            assert manifest["unclosed_spans"] == 2
            # closing retires the entries: the next bundle is clean
            span.__exit__(None, None, None)
            ctx.finish()
            bundle2 = rec.dump(reason="later", force=True)
            lines2 = [json.loads(ln) for ln in
                      open(os.path.join(bundle2, "events.jsonl"))]
            assert not any(e.get("unclosed") for e in lines2)
            # the closed spans themselves DID land in the ring
            assert any(e.get("kind") == "span"
                       and e.get("name") == "request"
                       for e in lines2)
        finally:
            rec.close()


# ---------------------------------------------------------------------------
# SLO layer: burn rates, config schema, alert wiring
# ---------------------------------------------------------------------------

def _fast_windows():
    return [BurnWindow(short_s=5.0, long_s=10.0, factor=2.0)]


class TestSLOMonitor:
    def _latency_fixture(self):
        reg = MetricsRegistry()
        h = reg.histogram("serving_latency_seconds", help="t",
                          labels={"endpoint": "predict"})
        clock = [0.0]
        mon = SLOMonitor(
            reg, [SLO(name="predict_fast", objective=0.9,
                      threshold_s=0.05,
                      labels={"endpoint": "predict"}, window_s=60.0,
                      windows=_fast_windows())],
            clock=lambda: clock[0], min_eval_interval_s=0.0)
        return reg, h, clock, mon

    def test_healthy_traffic_never_breaches(self):
        reg, h, clock, mon = self._latency_fixture()
        for t in range(10):
            for _ in range(50):
                h.record(0.01)
            clock[0] = float(t)
            assert mon.evaluate() == []
        assert not mon.status()[0]["breached"]

    def test_burn_rate_breach_and_recovery(self):
        reg, h, clock, mon = self._latency_fixture()
        for _ in range(50):
            h.record(0.01)
        clock[0] = 1.0
        mon.evaluate()
        # budget is 10%; 100% of fresh traffic is bad ⇒ burn 10x,
        # past the 2x factor on BOTH windows
        for i in range(50):
            h.record(0.5, exemplar={"trace_id": f"slow{i:02d}"})
        clock[0] = 2.0
        changes = mon.evaluate()
        assert [c["event"] for c in changes] == ["breach"]
        assert changes[0]["slo"] == "predict_fast"
        assert changes[0]["burn_long"] > 2.0
        # the page ships concrete offenders from the exemplars
        assert changes[0]["traces"]
        assert all(t.startswith("slow") for t in changes[0]["traces"])
        st = mon.status()[0]
        assert st["breached"] and st["burn_rates"]
        # breach gauge + burn-rate gauges live on the registry
        assert reg.get("slo_breach",
                       labels={"slo": "predict_fast"}).value() == 1.0
        assert reg.get("slo_burn_rate",
                       labels={"slo": "predict_fast",
                               "window": "10s"}).value() > 2.0
        # no re-fire while still breached
        for _ in range(10):
            h.record(0.5)
        clock[0] = 3.0
        assert all(c["event"] != "breach" for c in mon.evaluate())
        # recovery: enough good traffic drowns the burn once both
        # windows have moved past the incident's samples
        for _ in range(5000):
            h.record(0.01)
        clock[0] = 20.0
        changes = mon.evaluate()
        assert [c["event"] for c in changes] == ["recover"]
        assert not mon.status()[0]["breached"]

    def test_short_window_clears_stale_incident(self):
        """Multi-window semantics: once the burst stops, the short
        window goes quiet and the incident CLEARS — even while the
        long window still remembers enough burn to exceed the
        factor. A stale incident cannot keep paging."""
        reg, h, clock, mon = self._latency_fixture()
        clock[0] = 0.0
        mon.evaluate()                     # baseline sample at t=0
        for _ in range(50):
            h.record(0.5)                  # the burst
        clock[0] = 2.0
        changes = mon.evaluate()           # mid-incident: pages
        assert [c["event"] for c in changes] == ["breach"]
        # burst ends; nothing recorded. At t=7 the short window's
        # base is the post-burst sample (t=2, delta 0 ⇒ burn 0)
        # while the long window's base is still t=0 (burn 10x)
        clock[0] = 7.0
        changes = mon.evaluate()
        assert [c["event"] for c in changes] == ["recover"]
        assert not mon.status()[0]["breached"]

    def test_availability_slo_over_counters(self):
        reg = MetricsRegistry()
        total = reg.counter("serving_requests_total", help="r",
                            labels={"endpoint": "predict"})
        errs = reg.counter("serving_errors_total", help="e",
                           labels={"endpoint": "predict"})
        clock = [0.0]
        mon = SLOMonitor(
            reg, [SLO(name="availability", objective=0.95,
                      labels={"endpoint": "predict"}, window_s=60.0,
                      windows=_fast_windows())],
            clock=lambda: clock[0], min_eval_interval_s=0.0)
        total.inc(100)
        clock[0] = 1.0
        mon.evaluate()
        total.inc(100)
        errs.inc(50)                       # 50% errors vs 5% budget
        clock[0] = 2.0
        changes = mon.evaluate()
        assert [c["event"] for c in changes] == ["breach"]

    def test_from_config_human_units(self):
        slo = SLO.from_config({"name": "p99", "objective": 0.99,
                               "threshold_ms": 50,
                               "window_m": 30,
                               "endpoint": "predict/iris/v1"})
        assert slo.threshold_s == 0.05
        assert slo.window_s == 1800.0
        assert slo.labels == {"endpoint": "predict/iris/v1"}
        with pytest.raises(ValueError, match="unknown SLO config"):
            SLO.from_config({"name": "x", "objectve": 0.9})
        with pytest.raises(ValueError, match="objective"):
            SLO.from_config({"name": "x", "objective": 1.5})

    def test_monitor_from_config_json_and_file(self, tmp_path):
        rules = [{"name": "a", "objective": 0.9,
                  "threshold_ms": 10.0}]
        reg = MetricsRegistry()
        m1 = SLOMonitor.from_config(reg, json.dumps(rules))
        assert [s["name"] for s in m1.status()] == ["a"]
        p = tmp_path / "slo.json"
        p.write_text(json.dumps({"slos": rules}))
        m2 = SLOMonitor.from_config(MetricsRegistry(), str(p))
        assert [s["name"] for s in m2.status()] == ["a"]

    def test_install_registers_alert_rules(self):
        from deeplearning4j_tpu.observability.alerts import (
            AlertManager)
        reg, h, clock, mon = self._latency_fixture()
        mgr = AlertManager(registry=reg)
        mon.install(mgr)
        for _ in range(20):
            h.record(0.01)
        clock[0] = 1.0
        mon.evaluate()
        for _ in range(20):
            h.record(0.5)
        clock[0] = 2.0
        mon.evaluate()
        # the slo_breach pull gauge feeds the standard alert pipeline
        firing = mgr.evaluate()
        assert any(a["name"] == "slo_burn:predict_fast"
                   for a in firing)


class TestSLOEndToEnd:
    def test_breach_degrades_healthz_with_bundled_traces(
            self, tmp_path):
        """The acceptance chain: slow traffic ⇒ burn-rate breach ⇒
        /healthz degraded, offending trace ids in the breach payload
        AND captured in a flight-recorder bundle."""
        tracer = Tracer(enabled=False)
        reg = ModelRegistry()
        reg.register("echo", EchoModel(delay=0.03))
        metrics = ServingMetrics()
        slos = SLOMonitor(
            metrics.registry,
            [SLO(name="echo_fast", objective=0.5, threshold_s=1e-4,
                 labels={"endpoint": "predict/echo/v1"},
                 window_s=60.0,
                 windows=[BurnWindow(short_s=0.3, long_s=0.6,
                                     factor=1.5)])],
            min_eval_interval_s=0.0)
        rec = flight_recorder.install(flight_recorder.FlightRecorder(
            out_dir=str(tmp_path), tracer=tracer,
            registry=metrics.registry, min_dump_interval_s=0.0))
        srv = ModelServer(reg, port=0, wait_ms=1.0, sample_rate=1.0,
                          metrics=metrics, slos=slos,
                          tracer=tracer).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            body, _ = _get(base, "/healthz")
            assert body["status"] == "ok"
            assert body["slos"][0]["name"] == "echo_fast"
            # keep bad traffic FLOWING while polling: burn must show
            # on the short window too (a stopped burst cannot page —
            # that is the multi-window point)
            traced = set()
            deadline = time.monotonic() + 10.0
            while True:
                _, _, headers = _post(
                    base, "/v1/predict",
                    {"model": "echo", "inputs": [[1.0, 2.0]]})
                traced.add(_trace_id_from(headers)[0])
                body, _ = _get(base, "/healthz")
                if body["status"] == "degraded" \
                        or time.monotonic() > deadline:
                    break
            assert body["status"] == "degraded"
            breach = body["slo_breaches"][0]
            assert breach["name"] == "echo_fast" and \
                breach["breached"]
            # the bundle landed, carrying the offending trace ids
            assert rec.dumps, "no flight-recorder bundle on breach"
            lines = [json.loads(ln) for ln in
                     open(os.path.join(rec.dumps[-1],
                                       "events.jsonl"))]
            ev = next(e for e in lines if e["kind"] == "slo_breach")
            assert ev["slo"] == "echo_fast"
            assert ev["traces"] and set(ev["traces"]) <= traced
        finally:
            srv.stop(drain=True, timeout=10.0)
            flight_recorder.uninstall()


# ---------------------------------------------------------------------------
# trace_report CLI
# ---------------------------------------------------------------------------

class TestTraceReportCLI:
    def _make_spans(self, tmp_path, n=5):
        tr = Tracer(enabled=False)
        s = BatchScheduler(EchoModel(), max_batch_size=4,
                           queue_limit=16, wait_ms=1.0,
                           name="predict")
        ids = []
        try:
            for _ in range(n):
                ctx = RequestContext(sampled=True,
                                     route="/v1/predict", tracer=tr)
                s.predict(np.ones((1, 4), np.float32), ctx=ctx)
                ctx.finish()
                ids.append(ctx.trace_id)
        finally:
            s.shutdown()
        path = str(tmp_path / "spans.jsonl")
        tr.write_jsonl(path)
        return tr, path, ids

    def test_file_report_phases_and_tree(self, tmp_path, capsys):
        from tools.trace_report import main
        tr, path, ids = self._make_spans(tmp_path)
        assert main([path]) == 0
        out = capsys.readouterr().out
        assert f"{len(ids)} trace(s)" in out
        for phase in PREDICT_PHASES:
            assert phase in out
        assert "dominant phase:" in out
        assert "request" in out                 # rendered tree root

    def test_trace_id_prefix_selection(self, tmp_path, capsys):
        from tools.trace_report import main
        tr, path, ids = self._make_spans(tmp_path, n=3)
        assert main([path, "--trace", ids[0][:12]]) == 0
        out = capsys.readouterr().out
        assert f"trace {ids[0]}" in out
        assert ids[1] not in out
        assert main([path, "--trace", "ffffnotthere"]) == 0
        assert "no trace matching" in capsys.readouterr().out

    def test_chrome_trace_input(self, tmp_path, capsys):
        from tools.trace_report import main
        tr, _, ids = self._make_spans(tmp_path, n=2)
        chrome = str(tmp_path / "trace.json")
        tr.export_chrome_trace(chrome)
        assert main([chrome]) == 0
        out = capsys.readouterr().out
        assert "2 trace(s)" in out and "device_step" in out

    def test_url_mode_against_live_server(self, capsys):
        from tools.trace_report import main
        reg = ModelRegistry()
        reg.register("iris", _mlp())
        srv = ModelServer(reg, port=0, wait_ms=2.0, sample_rate=1.0,
                          slow_ms=0.0,
                          tracer=Tracer(enabled=False)).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            for _ in range(3):
                _post(base, "/v1/predict",
                      {"model": "iris", "inputs": [[1, 2, 3, 4]]})
            assert main(["--url", base]) == 0
            out = capsys.readouterr().out
            assert "endpoint predict/iris/v1" in out
            assert "dominant:" in out
        finally:
            srv.stop(drain=True, timeout=10.0)

    def test_usage_errors(self, tmp_path, capsys):
        from tools.trace_report import main
        assert main([]) == 2                        # neither input
        assert main(["x.jsonl", "--url", "http://h"]) == 2   # both
        assert main([str(tmp_path / "missing.jsonl")]) == 2


# ---------------------------------------------------------------------------
# UI surface: SLO verdicts ride the dashboard health payload
# ---------------------------------------------------------------------------

class TestUIHealthSLOs:
    def test_health_payload_degrades_on_breach(self):
        from deeplearning4j_tpu.ui.server import UIServer
        reg = MetricsRegistry()
        h = reg.histogram("serving_latency_seconds", help="t",
                          labels={"endpoint": "predict"})
        clock = [0.0]
        mon = SLOMonitor(
            reg, [SLO(name="ui_slo", objective=0.9, threshold_s=0.05,
                      labels={"endpoint": "predict"}, window_s=60.0,
                      windows=_fast_windows())],
            clock=lambda: clock[0], min_eval_interval_s=0.0)
        ui = UIServer(port=0)
        ui.attach_health(slos=mon)
        payload = ui.health_payload()
        assert payload["status"] == "ok"
        assert payload["slos"][0]["name"] == "ui_slo"
        for _ in range(20):
            h.record(0.01)
        clock[0] = 1.0
        mon.evaluate()
        for _ in range(20):
            h.record(0.5)
        clock[0] = 2.0
        payload = ui.health_payload()
        assert payload["status"] == "degraded"
        assert payload["slos"][0]["breached"]
