"""Training plumbing: tBPTT state carry, gradient normalization,
per-layer updaters, masking, constraints, reproducibility."""

import os

import numpy as np
import pytest

import jax

from deeplearning4j_tpu import (ComputationGraph, MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.fetchers import (iris_data,
                                              synthetic_sequences)
from deeplearning4j_tpu.nn.conf import updaters
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (DenseLayer, DropoutLayer,
                                               LSTM, OutputLayer,
                                               RnnOutputLayer)


class TestTbptt:
    def test_tbptt_carries_state_across_chunks(self):
        """A memory task only solvable with cross-chunk state: the label
        depends on the FIRST timestep; tBPTT chunks of 5 over T=20 can
        only solve it if hidden state carries across chunks."""
        rng = np.random.default_rng(0)
        n, t = 512, 20
        first = rng.integers(0, 2, n)
        xs = rng.normal(0, 0.1, (n, t, 2)).astype(np.float32)
        xs[:, 0, 0] = first * 2.0 - 1.0         # signal only at t=0
        ys = np.zeros((n, t, 2), np.float32)
        ys[np.arange(n), :, :] = np.eye(2, dtype=np.float32)[first][:, None]

        conf = (NeuralNetConfiguration.builder()
                .set_seed(0)
                .updater(updaters.adam(0.01))
                .backprop_type("tbptt", fwd_length=5, bwd_length=5)
                .list()
                .layer(LSTM(n_out=12))
                .layer(RnnOutputLayer(n_out=2, loss="mcxent"))
                .set_input_type(InputType.recurrent(2, t))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.fit(xs, ys, epochs=10, batch_size=128)
        # accuracy on the LAST timestep (requires memory of t=0 across
        # 4 chunk boundaries)
        preds = np.asarray(net.output(xs[:256]))[:, -1, :]
        acc = (preds.argmax(1) == first[:256]).mean()
        assert acc > 0.9, acc

    def test_tbptt_iteration_count(self):
        xs, ys = synthetic_sequences(64, 20, 4, 3)
        ys_seq = ys[:, None, :].repeat(20, 1)
        conf = (NeuralNetConfiguration.builder()
                .updater(updaters.adam(0.01))
                .backprop_type("tbptt", fwd_length=8, bwd_length=8)
                .list()
                .layer(LSTM(n_out=8))
                .layer(RnnOutputLayer(n_out=3))
                .set_input_type(InputType.recurrent(4, 20))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.fit(xs, ys_seq, epochs=1, batch_size=64)
        # 20 steps / fwd 8 → 3 chunks = 3 iterations
        assert net.iteration_count == 3


class TestGradientNormalization:
    def test_clip_l2_per_layer_bounds_update(self):
        xs, ys = iris_data()
        conf = (NeuralNetConfiguration.builder()
                .updater(updaters.sgd(1.0))     # huge lr
                .gradient_normalization("clip_l2_per_layer", 1e-4)
                .list()
                .layer(DenseLayer(n_out=8, activation="relu"))
                .layer(OutputLayer(n_out=3))
                .set_input_type(InputType.feed_forward(4))
                .build())
        net = MultiLayerNetwork(conf).init()
        before = net.params_flat()
        net.fit(xs[:32], ys[:32], epochs=1, batch_size=32)
        delta = np.abs(net.params_flat() - before).max()
        # grad norm clipped to 1e-4, lr=1 → tiny updates
        assert delta < 1e-3, delta

    def test_unknown_kind_raises(self):
        from deeplearning4j_tpu.train.gradnorm import (
            normalize_layer_gradients)
        import jax.numpy as jnp
        with pytest.raises(ValueError):
            normalize_layer_gradients({"W": jnp.ones((2, 2))}, "bogus", 1.0)


class TestPerLayerUpdaters:
    def test_mln_frozen_lr_layer(self):
        xs, ys = iris_data()
        conf = (NeuralNetConfiguration.builder()
                .updater(updaters.adam(0.05))
                .list()
                .layer(DenseLayer(n_out=8, activation="relu",
                                  updater=updaters.sgd(0.0)))
                .layer(OutputLayer(n_out=3))
                .set_input_type(InputType.feed_forward(4))
                .build())
        net = MultiLayerNetwork(conf).init()
        w0 = np.asarray(net.params[0]["W"]).copy()
        net.fit(xs[:64], ys[:64], epochs=3, batch_size=32)
        np.testing.assert_allclose(np.asarray(net.params[0]["W"]), w0)
        # output layer did move
        assert np.abs(np.asarray(net.params[1]["W"])).sum() > 0

    def test_graph_frozen_lr_layer(self):
        xs, ys = iris_data()
        g = (NeuralNetConfiguration.builder()
             .updater(updaters.adam(0.05))
             .graph_builder()
             .add_inputs("in")
             .add_layer("d", DenseLayer(n_out=8, activation="relu",
                                        updater=updaters.sgd(0.0)), "in")
             .add_layer("out", OutputLayer(n_out=3), "d")
             .set_outputs("out")
             .set_input_types(InputType.feed_forward(4))
             .build())
        cg = ComputationGraph(g).init()
        w0 = np.asarray(cg.params["d"]["W"]).copy()
        cg.fit(DataSet(xs[:64], ys[:64]), epochs=3)
        np.testing.assert_allclose(np.asarray(cg.params["d"]["W"]), w0)


class TestReproducibility:
    def test_graph_dropout_deterministic_given_seed(self):
        xs, ys = iris_data()

        def run():
            g = (NeuralNetConfiguration.builder()
                 .set_seed(99)
                 .updater(updaters.adam(0.01))
                 .graph_builder()
                 .add_inputs("in")
                 .add_layer("d", DenseLayer(n_out=16, activation="relu",
                                            dropout=0.5), "in")
                 .add_layer("out", OutputLayer(n_out=3), "d")
                 .set_outputs("out")
                 .set_input_types(InputType.feed_forward(4))
                 .build())
            cg = ComputationGraph(g).init()
            cg.fit(DataSet(xs[:64], ys[:64]), epochs=3)
            return np.asarray(cg.params["d"]["W"])

        np.testing.assert_allclose(run(), run())

    def test_mln_training_deterministic_given_seed(self):
        xs, ys = iris_data()

        def run():
            conf = (NeuralNetConfiguration.builder()
                    .set_seed(7).updater(updaters.adam(0.01))
                    .list()
                    .layer(DenseLayer(n_out=8, activation="relu",
                                      dropout=0.3))
                    .layer(OutputLayer(n_out=3))
                    .set_input_type(InputType.feed_forward(4))
                    .build())
            net = MultiLayerNetwork(conf).init()
            net.fit(xs[:64], ys[:64], epochs=2, batch_size=32)
            return net.params_flat()

        np.testing.assert_allclose(run(), run())


class TestConstraints:
    def test_max_norm_constraint_applied(self):
        xs, ys = iris_data()
        conf = (NeuralNetConfiguration.builder()
                .updater(updaters.sgd(0.5))
                .list()
                .layer(DenseLayer(n_out=8, activation="relu",
                                  constraints=({"type": "max_norm",
                                                "max_norm": 0.5},)))
                .layer(OutputLayer(n_out=3))
                .set_input_type(InputType.feed_forward(4))
                .build())
        net = MultiLayerNetwork(conf).init()
        net.fit(xs[:64], ys[:64], epochs=5, batch_size=32)
        w = np.asarray(net.params[0]["W"])
        norms = np.sqrt((w ** 2).sum(axis=0))
        assert (norms <= 0.5 + 1e-5).all(), norms


class TestMasking:
    def test_masked_rnn_loss_ignores_padded_steps(self):
        xs, ys = synthetic_sequences(32, 10, 4, 3)
        ys_seq = ys[:, None, :].repeat(10, 1)
        conf = (NeuralNetConfiguration.builder()
                .updater(updaters.adam(0.01)).list()
                .layer(LSTM(n_out=8))
                .layer(RnnOutputLayer(n_out=3))
                .set_input_type(InputType.recurrent(4, 10))
                .build())
        net = MultiLayerNetwork(conf).init()
        # full mask vs zero-padded tail with mask: padded version's score
        # must equal the truncated version's score on the valid prefix
        mask = np.ones((32, 10), np.float32)
        mask[:, 6:] = 0.0
        xs_pad = xs.copy()
        xs_pad[:, 6:] = 0.0
        s_masked = net.score(DataSet(xs_pad, ys_seq, labels_mask=mask,
                                     features_mask=mask))
        # corrupt the padded region — masked score must not change
        xs_garbage = xs_pad.copy()
        xs_garbage[:, 6:] = 99.0
        ys_garbage = ys_seq.copy()
        ys_garbage[:, 6:] = 5.0
        s_garbage = net.score(DataSet(xs_garbage, ys_garbage,
                                      labels_mask=mask,
                                      features_mask=mask))
        np.testing.assert_allclose(s_masked, s_garbage, rtol=1e-5)


class TestCenterLossGraph:
    def test_center_loss_updates_centers_in_graph(self):
        """CenterLossOutputLayer in a ComputationGraph must apply the
        center term and EMA-update centers (FaceNet zoo path)."""
        import numpy as np
        from deeplearning4j_tpu import (ComputationGraph,
                                        NeuralNetConfiguration)
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.data.fetchers import iris_data
        from deeplearning4j_tpu.nn.conf import updaters
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import (
            CenterLossOutputLayer, DenseLayer)
        xs, ys = iris_data()
        g = (NeuralNetConfiguration.builder().set_seed(0)
             .updater(updaters.adam(0.05)).graph_builder()
             .add_inputs("in")
             .add_layer("h", DenseLayer(n_out=8, activation="relu"),
                        "in")
             .add_layer("out", CenterLossOutputLayer(n_out=3,
                                                     lambda_=0.01), "h")
             .set_outputs("out")
             .set_input_types(InputType.feed_forward(4)).build())
        cg = ComputationGraph(g).init()
        centers0 = np.asarray(cg.state["out"]["centers"]).copy()
        cg.fit(DataSet(xs[:120], ys[:120]), epochs=120)
        centers1 = np.asarray(cg.state["out"]["centers"])
        assert np.abs(centers1 - centers0).max() > 1e-3
        assert cg.evaluate(DataSet(xs[120:], ys[120:])).accuracy() > 0.75


class TestSecondOrderOptimizers:
    """OptimizationAlgorithm parity (reference nn/api/
    OptimizationAlgorithm.java:26 + BackTrackLineSearch): LBFGS, CG,
    and line gradient descent must all fit iris to high accuracy."""

    def _net(self):
        # small L2 keeps the full-batch optimizers out of sharp
        # overfit minima (the reference pairs these with regularization)
        conf = (NeuralNetConfiguration.builder().set_seed(0)
                .updater(updaters.sgd(0.1)).l2(1e-3).list()
                .layer(DenseLayer(n_out=12, activation="tanh"))
                .layer(OutputLayer(n_out=3))
                .set_input_type(InputType.feed_forward(4)).build())
        return MultiLayerNetwork(conf).init()

    @pytest.mark.parametrize("algo", ["lbfgs", "conjugate_gradient",
                                      "line_gradient_descent"])
    def test_fits_iris(self, algo):
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.train.second_order import optimize
        xs, ys = iris_data()
        net = self._net()
        hist = optimize(net, DataSet(xs[:120], ys[:120]),
                        algorithm=algo, iterations=150)
        assert hist[-1] < hist[0] * 0.5, hist[:3] + hist[-3:]
        floor = 0.75 if algo == "line_gradient_descent" else 0.85
        assert net.evaluate(xs[120:], ys[120:]).accuracy() > floor

    def test_lbfgs_on_graph(self):
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.train.second_order import optimize
        xs, ys = iris_data()
        g = (NeuralNetConfiguration.builder().set_seed(0)
             .updater(updaters.sgd(0.1)).l2(1e-3).graph_builder()
             .add_inputs("in")
             .add_layer("h", DenseLayer(n_out=12, activation="tanh"),
                        "in")
             .add_layer("out", OutputLayer(n_out=3), "h")
             .set_outputs("out")
             .set_input_types(InputType.feed_forward(4)).build())
        cg = ComputationGraph(g).init()
        optimize(cg, DataSet(xs[:120], ys[:120]), algorithm="lbfgs",
                 iterations=150)
        assert cg.evaluate(DataSet(xs[120:], ys[120:])).accuracy() > 0.85

    def test_unknown_algorithm_raises(self):
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.train.second_order import optimize
        xs, ys = iris_data()
        with pytest.raises(ValueError, match="newton"):
            optimize(self._net(), DataSet(xs, ys), algorithm="newton")


class TestBf16Policy:
    """The MXU-native mixed-precision policy (dtypes.tpu_bf16: bf16
    compute, f32 params) must train to the same quality as f32."""

    def test_bf16_trains_iris(self):
        from deeplearning4j_tpu import dtypes
        xs, ys = iris_data()
        with dtypes.policy_scope(dtypes.tpu_bf16()):
            conf = (NeuralNetConfiguration.builder().set_seed(0)
                    .updater(updaters.adam(0.05)).list()
                    .layer(DenseLayer(n_out=16, activation="relu"))
                    .layer(OutputLayer(n_out=3))
                    .set_input_type(InputType.feed_forward(4)).build())
            net = MultiLayerNetwork(conf).init()
            net.fit(xs[:120], ys[:120], epochs=150)
            acc = net.evaluate(xs[120:], ys[120:]).accuracy()
        assert acc > 0.85, acc
        # params stayed f32 (the policy split)
        import jax.numpy as jnp
        assert net.params[0]["W"].dtype == jnp.float32

    def test_bf16_conv_forward_close_to_f32(self):
        from deeplearning4j_tpu import dtypes
        from deeplearning4j_tpu.nn.conf.layers import (ConvolutionLayer,
                                                       SubsamplingLayer)

        def build():
            conf = (NeuralNetConfiguration.builder().set_seed(0)
                    .updater(updaters.adam(0.01)).list()
                    .layer(ConvolutionLayer(n_out=8, kernel=(3, 3),
                                            activation="relu"))
                    .layer(SubsamplingLayer(kernel=(2, 2),
                                            stride=(2, 2)))
                    .layer(DenseLayer(n_out=16, activation="relu"))
                    .layer(OutputLayer(n_out=3))
                    .set_input_type(
                        InputType.convolutional_flat(8, 8, 1)).build())
            return MultiLayerNetwork(conf).init()

        x = np.random.default_rng(0).normal(
            0, 1, (4, 64)).astype(np.float32)
        f32_out = np.asarray(build().output(x))
        with dtypes.policy_scope(dtypes.tpu_bf16()):
            bf16_out = np.asarray(build().output(x))
        # same init (f32 params) — bf16 compute rounds to ~2-3 decimals
        np.testing.assert_allclose(bf16_out, f32_out, rtol=0.05,
                                   atol=0.02)


class TestElasticTrainer:
    """Preemption-aware elastic loop (train/fault_tolerance.py): the
    TPU-native replacement for the reference's minimal failure story
    (InvalidScore termination + Spark task retry)."""

    def _net(self, lr=0.05):
        conf = (NeuralNetConfiguration.builder().set_seed(0)
                .updater(updaters.adam(lr)).list()
                .layer(DenseLayer(n_out=8, activation="relu"))
                .layer(OutputLayer(n_out=3))
                .set_input_type(InputType.feed_forward(4)).build())
        return MultiLayerNetwork(conf).init()

    def _iter(self):
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.data.iterators import ListDataSetIterator
        xs, ys = iris_data()
        return ListDataSetIterator(DataSet(xs[:120], ys[:120])
                                   .batch_by(40))

    def test_periodic_checkpoints_and_prune(self, tmp_path):
        from deeplearning4j_tpu.train.fault_tolerance import (
            ElasticTrainer)
        t = ElasticTrainer(self._net(), str(tmp_path), save_every=3,
                           keep=2)
        t.fit(self._iter(), epochs=8)        # 24 iterations
        cks = [f for f in os.listdir(tmp_path) if f.endswith(".zip")]
        assert len(cks) == 2                  # pruned to keep
        assert t.latest_checkpoint().endswith("ckpt_24.zip")

    def test_resume_from_checkpoint(self, tmp_path):
        from deeplearning4j_tpu.train.fault_tolerance import (
            ElasticTrainer)
        net1 = self._net()
        t1 = ElasticTrainer(net1, str(tmp_path), save_every=5)
        t1.fit(self._iter(), epochs=5)       # 15 iterations
        it1 = net1.iteration_count
        p1 = net1.params_flat()
        # a fresh process/model resumes where the last one stopped
        net2 = self._net()
        t2 = ElasticTrainer(net2, str(tmp_path))
        assert net2.iteration_count == it1   # restored
        np.testing.assert_allclose(net2.params_flat(), p1, rtol=1e-6)
        t2.fit(self._iter(), epochs=2)
        assert net2.iteration_count > it1

    def test_nan_rollback_recovers(self, tmp_path):
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.train.fault_tolerance import (
            ElasticTrainer)
        xs, ys = iris_data()
        good = DataSet(xs[:40], ys[:40])
        poison = DataSet(np.full((8, 4), np.inf, np.float32), ys[:8])
        t = ElasticTrainer(self._net(), str(tmp_path), save_every=1)

        class It:
            def __init__(self):
                self.batches = [good, poison, good, good]

            def reset(self):
                pass

            def __iter__(self):
                return iter(self.batches)

        t.fit(It(), epochs=1)
        assert t.total_rollbacks == 1
        # the incident counter decayed after healthy iterations (the
        # bound is per-divergence, not per-lifetime)
        assert t.rollbacks == 0
        # params recovered to a finite state and training continued
        assert np.isfinite(t.model.params_flat()).all()

    def test_sigterm_checkpoints_and_stops(self, tmp_path):
        import signal as _signal

        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.train.fault_tolerance import (
            ElasticTrainer)
        xs, ys = iris_data()
        t = ElasticTrainer(self._net(), str(tmp_path), save_every=1000)

        class It:
            """Raises SIGTERM mid-epoch (the preemption notice)."""

            def __init__(self):
                self.n = 0

            def reset(self):
                self.n = 0

            def __iter__(self):
                for i in range(100):
                    if i == 3:
                        _signal.raise_signal(_signal.SIGTERM)
                    self.n += 1
                    yield DataSet(xs[:40], ys[:40])

        it = It()
        t.fit(it, epochs=5)
        # stopped promptly after the signal, not after 500 batches
        assert it.n <= 5
        # and the grace-window checkpoint exists at the stop iteration
        assert t.latest_checkpoint().endswith(
            f"ckpt_{t.model.iteration_count}.zip")

    class _KillAfter:
        """Deterministic iterator that requests a stop after N total
        batches — simulates preemption at an exact data position."""

        def __init__(self, batches, trainer, kill_at):
            self.batches = batches
            self.trainer = trainer
            self.kill_at = kill_at
            self.total = 0

        def reset(self):
            pass

        def __iter__(self):
            for b in self.batches:
                yield b
                self.total += 1
                if self.total == self.kill_at:
                    self.trainer._stop_requested = True

    def _equivalence(self, make_model, make_batches, tmp_path,
                     kill_at=4, epochs=2, wrapper_fn=None):
        """kill-at-batch-k + resume must reproduce the uninterrupted
        run bit-for-bit (restart == uninterrupted; the data position
        rides in the checkpoint). SURVEY §4.3 regression discipline."""
        from deeplearning4j_tpu.train.fault_tolerance import (
            ElasticTrainer)
        # run A: uninterrupted
        mA = make_model()
        tA = ElasticTrainer(mA, str(tmp_path / "a"), save_every=1000,
                            wrapper=wrapper_fn(mA) if wrapper_fn else None)
        tA.fit(make_batches(), until_epoch=epochs)
        # run B: killed mid-epoch at batch kill_at, then resumed
        mB = make_model()
        tB = ElasticTrainer(mB, str(tmp_path / "b"), save_every=1000,
                            wrapper=wrapper_fn(mB) if wrapper_fn else None)
        tB.fit(self._KillAfter(make_batches(), tB, kill_at),
               until_epoch=epochs)
        assert mB.iteration_count < mA.iteration_count  # really killed
        mB2 = make_model()
        tB2 = ElasticTrainer(mB2, str(tmp_path / "b"),
                             wrapper=wrapper_fn(mB2) if wrapper_fn
                             else None)
        assert mB2.iteration_count == mB.iteration_count  # resumed
        tB2.fit(make_batches(), until_epoch=epochs)
        assert mB2.iteration_count == mA.iteration_count
        np.testing.assert_array_equal(
            np.asarray(mA.params_flat()), np.asarray(mB2.params_flat()))

    def _iris_batches(self):
        xs, ys = iris_data()
        return DataSet(xs[:120], ys[:120]).batch_by(40)  # 3 batches

    def test_restart_equals_uninterrupted_mln(self, tmp_path):
        self._equivalence(self._net, self._iris_batches, tmp_path)

    def test_restart_equals_uninterrupted_graph(self, tmp_path):
        def make_cg():
            conf = (NeuralNetConfiguration.builder().set_seed(0)
                    .updater(updaters.adam(0.05))
                    .graph_builder()
                    .add_inputs("in")
                    .add_layer("h", DenseLayer(n_out=8,
                                               activation="relu"), "in")
                    .add_layer("out", OutputLayer(n_out=3), "h")
                    .set_outputs("out")
                    .set_input_types(InputType.feed_forward(4)).build())
            return ComputationGraph(conf).init()

        self._equivalence(make_cg, self._iris_batches, tmp_path)

    def test_restart_equals_uninterrupted_parallel_wrapper(self,
                                                           tmp_path):
        if jax.device_count() < 8:
            pytest.skip("needs 8 virtual devices")
        from deeplearning4j_tpu.parallel.mesh import MeshSpec, build_mesh
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
        mesh = build_mesh(MeshSpec(data=8), jax.devices()[:8])
        self._equivalence(
            self._net, self._iris_batches, tmp_path,
            wrapper_fn=lambda m: ParallelWrapper(m, mesh,
                                                 prefetch_buffer=0))


class TestRollbackPersistence:
    """Round-3 verdict weak #5: restart == uninterrupted must hold
    THROUGH a rollback, not just for clean kills — the poison-skip
    set rides in the checkpoint (a rollback re-checkpoints
    immediately), and the deterministic-iterator contract the replay
    relies on is checked via a batch fingerprint."""

    def _net(self):
        conf = (NeuralNetConfiguration.builder().set_seed(0)
                .updater(updaters.adam(0.05)).list()
                .layer(DenseLayer(n_out=8, activation="relu"))
                .layer(OutputLayer(n_out=3))
                .set_input_type(InputType.feed_forward(4)).build())
        return MultiLayerNetwork(conf).init()

    def _batches(self):
        from deeplearning4j_tpu.data.dataset import DataSet
        xs, ys = iris_data()
        good = DataSet(xs[:120], ys[:120]).batch_by(40)   # 3 batches
        poison = DataSet(np.full((8, 4), np.inf, np.float32),
                         ys[:8])
        return [good[0], poison, good[1], good[2]]

    def test_restart_after_rollback_no_second_rollback(self, tmp_path):
        from deeplearning4j_tpu.train.fault_tolerance import (
            ElasticTrainer)

        # run A: uninterrupted (one rollback, poison skipped, done)
        mA = self._net()
        tA = ElasticTrainer(mA, str(tmp_path / "a"), save_every=1)
        tA.fit(list(self._batches()), until_epoch=1)
        assert tA.total_rollbacks == 1

        # run B: KILLED immediately after the rollback (before any
        # further training), then resumed in a fresh trainer
        mB = self._net()
        tB = ElasticTrainer(mB, str(tmp_path / "b"), save_every=1)
        boom = RuntimeError("simulated kill after rollback")
        orig = tB._rollback

        def kill_after_rollback():
            orig()
            raise boom
        tB._rollback = kill_after_rollback
        with pytest.raises(RuntimeError, match="simulated kill"):
            tB.fit(list(self._batches()), until_epoch=1)
        assert tB.total_rollbacks == 1

        mB2 = self._net()
        tB2 = ElasticTrainer(mB2, str(tmp_path / "b"), save_every=1)
        # the persisted skip set must already know the poison batch
        assert tB2._skip, "skip set did not survive the restart"
        tB2.fit(list(self._batches()), until_epoch=1)
        # ZERO additional rollbacks on resume...
        assert tB2.total_rollbacks == 0
        # ...and bit-identical final params vs the uninterrupted run
        np.testing.assert_array_equal(
            np.asarray(mA.params_flat()), np.asarray(mB2.params_flat()))

    def test_nondeterministic_iterator_fails_loudly(self, tmp_path):
        from deeplearning4j_tpu.data.dataset import DataSet
        from deeplearning4j_tpu.train.fault_tolerance import (
            ElasticTrainer)
        xs, ys = iris_data()
        batches = DataSet(xs[:120], ys[:120]).batch_by(40)

        # train 2 batches, checkpoint every step, then "restart" with
        # a REORDERED iterator: the replay fingerprint must catch it
        m = self._net()
        t = ElasticTrainer(m, str(tmp_path), save_every=1)

        class KillAfter2:
            def __init__(self, trainer):
                self.trainer = trainer
                self.n = 0

            def reset(self):
                pass

            def __iter__(self):
                for b in batches:
                    yield b
                    self.n += 1
                    if self.n == 2:
                        self.trainer._stop_requested = True

        t.fit(KillAfter2(t), until_epoch=1)

        m2 = self._net()
        t2 = ElasticTrainer(m2, str(tmp_path), save_every=1)
        reordered = [batches[1], batches[0], batches[2]]
        with pytest.raises(RuntimeError, match="not deterministic"):
            t2.fit(reordered, until_epoch=1)

    def test_fingerprint_covers_labels_and_all_arrays(self):
        """A replay that keeps features but substitutes labels (or a
        later MultiDataSet array) must change the fingerprint —
        otherwise resume silently trains on wrong targets (ADVICE
        r4)."""
        from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
        from deeplearning4j_tpu.train.fault_tolerance import _fingerprint
        rng = np.random.default_rng(0)
        x = rng.normal(0, 1, (8, 4)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
        base = _fingerprint(DataSet(x, y))
        assert base == _fingerprint(DataSet(x.copy(), y.copy()))
        y2 = np.roll(y, 1, axis=0)
        assert base != _fingerprint(DataSet(x, y2))

        x2 = rng.normal(0, 1, (8, 4)).astype(np.float32)
        mbase = _fingerprint(MultiDataSet([x, x2], [y]))
        x2b = x2.copy()
        x2b[3] += 1.0            # second FEATURE array changes
        assert mbase != _fingerprint(MultiDataSet([x, x2b], [y]))
        assert mbase != _fingerprint(MultiDataSet([x, x2], [y2]))
