"""Guarded TPU example: transformer-LM streaming generation, traced.

Every other example pins JAX_PLATFORMS=cpu (a wedged TPU tunnel must
not hang them). This one is the framework's front door to the
accelerator it is named for: it PROBES for a TPU in a subprocess with
a timeout — the only way a dead tunnel can be detected without
hanging this process — and either

- runs on the TPU it found, or
- prints the concrete reason (no TPU device / probe timed out /
  probe crashed) and falls back to CPU, same code path.

Either way it trains a small character LM briefly with the step
profiler attached (data-wait / dispatch / device-fence decomposition,
observability/step_profile.py), counts every XLA compile and
persistent-cache hit via the process-wide compile watch
(observability/compile_watch.py), streams a generation through the
bounded KV-cache session, and writes a Chrome trace (--trace, open
in Perfetto) of the whole run.

Run: python examples/tpu_transformer_generate.py [--trace trace.json]
"""

import argparse
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

TEXT = ("the quick brown fox jumps over the lazy dog and the cat "
        "sat on the mat while the dog ran in the park ") * 40

_PROBE = ("import jax\n"
          "d = jax.devices()[0]\n"
          "print(d.platform, '|', d.device_kind)\n")


def probe_tpu(timeout_s: float = 90.0):
    """(use_tpu, reason). Probed in a SUBPROCESS with a timeout: a
    wedged tunnel hangs the first backend touch forever, and that
    must cost this process at most ``timeout_s`` (the bench.py device
    -probe idiom)."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        return False, "JAX_PLATFORMS=cpu was requested explicitly"
    try:
        r = subprocess.run([sys.executable, "-c", _PROBE],
                           capture_output=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False, (f"device probe timed out after {timeout_s:.0f}s"
                       " (wedged TPU tunnel?)")
    if r.returncode != 0:
        tail = r.stderr.decode(errors="replace").strip().splitlines()
        return False, ("device probe failed: "
                       + (tail[-1] if tail else "no backend"))
    out = r.stdout.decode().strip().splitlines()[-1]
    platform, _, kind = out.partition("|")
    if "tpu" in platform.strip().lower():
        return True, f"TPU found: {kind.strip()}"
    return False, f"no TPU — first device is {out}"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq-len", type=int, default=32)
    ap.add_argument("--gen-tokens", type=int, default=24)
    ap.add_argument("--trace", default="tpu_generate_trace.json",
                    help="Chrome trace-event output path")
    ap.add_argument("--probe-timeout", type=float, default=90.0)
    args = ap.parse_args()

    use_tpu, reason = probe_tpu(args.probe_timeout)
    if use_tpu:
        print(f"running on TPU ({reason})")
    else:
        print(f"falling back to CPU: {reason}")
        os.environ["JAX_PLATFORMS"] = "cpu"
        from deeplearning4j_tpu.util.platform import pin_cpu_platform
        pin_cpu_platform()

    from deeplearning4j_tpu import (MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import updaters
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (
        EmbeddingSequenceLayer, RnnOutputLayer,
        TransformerEncoderLayer)
    from deeplearning4j_tpu.observability import (
        ProfilerListener, install_global_watch, trace)

    trace.enable()
    compile_stats = install_global_watch()

    chars = sorted(set(TEXT))
    V = len(chars)
    idx = {c: i for i, c in enumerate(chars)}
    ids = np.array([idx[c] for c in TEXT], np.int32)
    T = args.seq_len

    conf = (NeuralNetConfiguration.builder().set_seed(7)
            .updater(updaters.adam(3e-3)).list()
            .layer(EmbeddingSequenceLayer(n_in=V, n_out=32))
            .layer(TransformerEncoderLayer(n_heads=4, causal=True))
            .layer(TransformerEncoderLayer(n_heads=4, causal=True))
            .layer(RnnOutputLayer(n_out=V, loss="mcxent"))
            .set_input_type(InputType.recurrent(V, T)).build())
    net = MultiLayerNetwork(conf).init()
    profiler = ProfilerListener(frequency=8, report=False)
    net.set_listeners(profiler)

    rng = np.random.default_rng(0)
    starts = rng.integers(0, len(ids) - T - 1, 256)
    x = np.stack([ids[s:s + T] for s in starts]).astype(np.float32)
    y = np.eye(V, dtype=np.float32)[
        np.stack([ids[s + 1:s + T + 1] for s in starts])]
    with trace.span("train"):
        for epoch in range(args.epochs):
            for b in range(0, len(x), args.batch):
                net.fit(DataSet(x[b:b + args.batch],
                                y[b:b + args.batch]))
            print(f"epoch {epoch}: loss {float(net.score_value):.4f}")
    if profiler.reports:
        rep = profiler.reports[-1]
        print("step profile: "
              f"{rep['samples_per_sec']:.0f} samples/sec — "
              f"data_wait {rep['data_wait_ms']:.2f} ms, dispatch "
              f"{rep['dispatch_ms']:.2f} ms, device fence "
              f"{rep['device_fence_ms']:.2f} ms per report window")

    # streaming generation through the bounded KV-cache session; the
    # global compile watch counts its executables (a healthy session
    # compiles prefill + decode ONCE — the summary below shows it)
    prompt_txt = "the quick"
    prompt = np.array([[idx[c] for c in prompt_txt]], np.int32)
    n = args.gen_tokens
    sess = net.streaming_session(capacity=prompt.shape[1] + n, batch=1)
    with trace.span("generate"):
        out_ids = np.asarray(sess.generate(prompt, n))[0]
    text = "".join(chars[i] for i in out_ids)
    print(f"prompt: {prompt_txt!r}")
    print(f"generated: {text!r}")
    print(f"decode executables compiled for chunk lengths: "
          f"{sorted(sess._step_cache)}")

    s = compile_stats.summary()
    print(f"compile watch: {s['backend_compiles']} backend compiles, "
          f"{s['compile_secs']:.1f}s compiling, persistent cache "
          f"hits {s['persistent_cache_hits']}/{s['cache_requests']}")
    n_ev = trace.export_chrome_trace(args.trace)
    trace.disable()
    print(f"trace written: {args.trace} ({n_ev} events) — open in "
          "Perfetto / chrome://tracing")


if __name__ == "__main__":
    main()
