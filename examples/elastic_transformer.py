"""Preemption-safe transformer training — round-3 features end to end.

A small transformer classifier (SelfAttentionLayer — backed by the
Pallas flash kernels on TPU, exact blockwise attention elsewhere)
trained under :class:`ElasticTrainer`: atomic checkpoints carry the
DATA POSITION, so killing the run at any batch and re-running the same
command reproduces the uninterrupted run bit-for-bit (the property
`tests/test_training_plumbing.py` asserts for MLN/CG/ParallelWrapper).

Run: python examples/elastic_transformer.py [--epochs 3]
"""

import argparse
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from deeplearning4j_tpu.util.platform import pin_cpu_platform

pin_cpu_platform()   # dead TPU tunnel must not hang CPU-pinned runs

import numpy as np

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.nn.conf import updaters
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (GlobalPoolingLayer,
                                               OutputLayer,
                                               SelfAttentionLayer)
from deeplearning4j_tpu.train.fault_tolerance import ElasticTrainer


def make_net(seed=7):
    conf = (NeuralNetConfiguration.builder().set_seed(seed)
            .updater(updaters.adam(5e-3)).list()
            .layer(SelfAttentionLayer(n_out=16, n_heads=4))
            .layer(GlobalPoolingLayer(pooling="max"))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.recurrent(8, 12)).build())
    return MultiLayerNetwork(conf).init()


def make_data(n=384, t=12, f=8, seed=0):
    """Marker-retrieval task: the class is which of 3 marker vectors
    appears at a random position — attention's home turf."""
    rng = np.random.default_rng(seed)
    markers = rng.normal(0, 3.0, (3, f)).astype(np.float32)
    xs = rng.normal(0, 0.5, (n, t, f)).astype(np.float32)
    labels = rng.integers(0, 3, n)
    xs[np.arange(n), rng.integers(0, t, n)] = markers[labels]
    ys = np.eye(3, dtype=np.float32)[labels]
    return xs, ys


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=4)
    args = ap.parse_args()

    xs, ys = make_data()
    batches = DataSet(xs[:320], ys[:320]).batch_by(64)   # 5/epoch

    ckdir = tempfile.mkdtemp(prefix="elastic_")
    try:
        # --- run A: uninterrupted ---
        netA = make_net()
        ElasticTrainer(netA, os.path.join(ckdir, "a"),
                       save_every=1000).fit(batches,
                                            until_epoch=args.epochs)

        # --- run B: killed mid-epoch (simulated preemption), then the
        # SAME command re-run — resumes from the checkpointed data
        # position and finishes identically ---
        netB = make_net()
        tB = ElasticTrainer(netB, os.path.join(ckdir, "b"),
                            save_every=1000)

        class KillAt:
            def __init__(self, inner, at):
                self.inner, self.at, self.n = inner, at, 0

            def reset(self):
                pass

            def __iter__(self):
                for b in self.inner:
                    yield b
                    self.n += 1
                    if self.n == self.at:
                        tB._stop_requested = True   # SIGTERM analog

        tB.fit(KillAt(batches, 7), until_epoch=args.epochs)
        print(f"killed at iteration {netB.iteration_count} "
              f"(epoch {tB._epoch}, batch {tB._batch})")

        netB2 = make_net()
        ElasticTrainer(netB2, os.path.join(ckdir, "b")).fit(
            batches, until_epoch=args.epochs)     # same command again

        same = np.array_equal(np.asarray(netA.params_flat()),
                              np.asarray(netB2.params_flat()))
        print("restart == uninterrupted:", "OK" if same else "MISMATCH")
        assert same

        acc = netB2.evaluate(xs[320:], ys[320:]).accuracy()
        print(f"Accuracy after resume: {acc:.3f}")
        assert acc > 0.8 or args.epochs < 4   # 4 epochs converge
    finally:
        shutil.rmtree(ckdir, ignore_errors=True)


if __name__ == "__main__":
    main()
