"""Word2Vec over a text file (or a built-in demo corpus).

Mirrors the reference's Word2Vec example: sentence iterator →
tokenizer → builder → fit → nearest-word queries → save vectors.

Run: python examples/word2vec_text.py [--input corpus.txt]
"""

import os
import sys

# allow running straight from a repo checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from deeplearning4j_tpu.util.platform import pin_cpu_platform

pin_cpu_platform()   # dead TPU tunnel must not hang CPU-pinned runs

import argparse

from deeplearning4j_tpu.nlp import Word2Vec
from deeplearning4j_tpu.nlp.serializer import write_word_vectors
from deeplearning4j_tpu.nlp.tokenization import (CommonPreprocessor,
                                                 DefaultTokenizerFactory,
                                                 FileSentenceIterator,
                                                 ListSentenceIterator)

DEMO = [
    "the king rules the kingdom with the queen",
    "the queen advises the king on royal matters",
    "the cat chases the mouse through the house",
    "the mouse hides from the cat in the house",
    "the king and queen host a royal feast",
    "a cat and a mouse live in the old house",
] * 50


def main(path=None, out="/tmp/vectors.txt"):
    it = FileSentenceIterator(path) if path else ListSentenceIterator(DEMO)
    tf = DefaultTokenizerFactory()
    tf.set_token_pre_processor(CommonPreprocessor())
    w2v = (Word2Vec.builder()
           .layer_size(64)
           .window_size(5)
           .min_word_frequency(3)
           .negative_sample(5)
           .epochs(5)
           .sampling(0.0)
           .seed(42)
           .iterate(it)
           .tokenizer_factory(tf)
           .build())
    w2v.fit()
    print(f"vocab: {len(w2v.vocab)} words")
    for word in ("king", "cat"):
        if w2v.get_word_vector(word) is not None:
            print(f"nearest({word}):", w2v.words_nearest(word, 4))
    write_word_vectors(w2v, out)
    print(f"vectors written to {out}")


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--input", default=None)
    args = p.parse_args()
    main(args.input)
