"""Sequence-parallel language-model training — round-4 features end
to end.

A causal transformer LM built from the config DSL trains over a mesh
whose `seq` axis shards the TIME dimension across devices: the
standard ``ParallelWrapper`` traces the model under the
sequence-parallel context and ``SelfAttentionLayer`` rides ring flash
attention (exact global attention; Pallas kernels per chunk on TPU).
The batch is VARIABLE-LENGTH: key-padding mask chunks rotate around
the ring with their K/V blocks, and the masked loss denominator psums
globally. Training matches the single-device step to float tolerance
— the same property the dryrun regimes 8a–c assert.

Run: python examples/long_context_lm.py [--epochs 20]
(needs >= 4 devices; tests run it on a virtual 4-device CPU mesh)
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

# honor virtual-CPU-device runs even when a hardware plugin pins the
# platform (the env var alone is overridden by e.g. the axon plugin)
if "xla_force_host_platform_device_count" in os.environ.get(
        "XLA_FLAGS", "") and os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np


def make_net(seed=3):
    from deeplearning4j_tpu import (MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf import updaters
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (
        EmbeddingSequenceLayer, RnnOutputLayer,
        TransformerEncoderLayer)
    conf = (NeuralNetConfiguration.builder().set_seed(seed)
            .updater(updaters.adam(1e-2)).list()
            .layer(EmbeddingSequenceLayer(n_in=VOCAB, n_out=16))
            .layer(TransformerEncoderLayer(n_heads=4, causal=True))
            .layer(TransformerEncoderLayer(n_heads=4, causal=True))
            .layer(RnnOutputLayer(n_out=VOCAB, loss="mcxent"))
            .set_input_type(InputType.recurrent(VOCAB, T)).build())
    return MultiLayerNetwork(conf).init()


VOCAB, T, B = 11, 32, 8


def make_data(seed=0):
    """Cyclic-successor LM: token[t+1] = (token[t] + k) mod V with a
    per-sequence stride k the model must infer from context — causal
    attention's bread and butter. Sequences are RAGGED (variable
    length), exercising the rotating mask chunks."""
    rng = np.random.default_rng(seed)
    toks = np.zeros((B, T), np.int64)
    for b in range(B):
        k = rng.integers(1, 4)
        toks[b, 0] = rng.integers(0, VOCAB)
        for t in range(1, T):
            toks[b, t] = (toks[b, t - 1] + k) % VOCAB
    x = toks.astype("float32")           # int ids -> embedding layer
    y = np.eye(VOCAB, dtype="float32")[np.roll(toks, -1, axis=1)]
    mask = np.ones((B, T), np.float32)
    lengths = rng.integers(T // 2, T, B)   # ragged, < T: the final
    for b in range(B):                     # position never has a
        mask[b, lengths[b]:] = 0.0         # next-token target anyway
    return x, y, mask


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=20)
    args = ap.parse_args()
    epochs = max(2, args.epochs)     # need >=2 to show loss movement

    if jax.device_count() < 4:
        raise SystemExit("needs >= 4 devices (set XLA_FLAGS="
                         "--xla_force_host_platform_device_count=4)")

    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.data.iterators import ListDataSetIterator
    from deeplearning4j_tpu.parallel.mesh import MeshSpec, build_mesh
    from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

    x, y, mask = make_data()
    ds = DataSet(x, y, mask, mask)

    mesh = build_mesh(MeshSpec(data=2, seq=2), jax.devices()[:4])
    print(f"mesh: data=2 x seq=2 over {mesh.devices.size} devices — "
          f"T={T} sharded 2-way, ragged lengths "
          f"{[int(mask[b].sum()) for b in range(B)]}")

    net = make_net()
    pw = ParallelWrapper(net, mesh, prefetch_buffer=0)
    pw.fit(ListDataSetIterator([ds]), epochs=1)
    first = float(net.score_value)
    pw.fit(ListDataSetIterator([ds]), epochs=epochs - 1)
    last = float(net.score_value)
    print(f"seq-parallel masked LM loss: {first:.3f} -> {last:.3f}")

    # the headline property: identical to the single-device step
    single = make_net()
    for _ in range(epochs):
        single.fit(ds)
    same = np.allclose(np.asarray(net.params_flat()),
                       np.asarray(single.params_flat()),
                       rtol=2e-4, atol=2e-5)
    print(f"matches single-device params: {same}")
    if not same or not last < first:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
