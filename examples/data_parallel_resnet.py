"""Data-parallel ResNet50 over a device mesh.

The BASELINE.json headline workload: zoo ResNet50 trained via the
ParallelWrapper equivalent — batch sharded over the mesh's 'data'
axis, gradient all-reduce inserted by XLA over ICI. Runs on however
many devices are available (single chip included; for a virtual
multi-device run: XLA_FLAGS=--xla_force_host_platform_device_count=8
JAX_PLATFORMS=cpu).

Run: python examples/data_parallel_resnet.py [--img 64] [--steps 10]
"""

import os
import sys

# allow running straight from a repo checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import argparse

import jax

# honor virtual-CPU-device runs even when a hardware plugin pins the
# platform (the env var alone is overridden by e.g. the axon plugin)
if "xla_force_host_platform_device_count" in os.environ.get(
        "XLA_FLAGS", "") and os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np

from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.data.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf import updaters
from deeplearning4j_tpu.parallel.mesh import MeshSpec, build_mesh
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
from deeplearning4j_tpu.train.listeners import PerformanceListener
from deeplearning4j_tpu.zoo import ResNet50


def main(img=64, batch_per_device=8, steps=10, n_classes=100):
    n_dev = jax.device_count()
    mesh = build_mesh(MeshSpec(data=n_dev))
    print(f"{n_dev} devices, mesh {dict(mesh.shape)}")

    net = ResNet50(n_classes=n_classes, input_shape=(img, img, 3),
                   updater=updaters.nesterovs(0.1, 0.9)).init()
    rng = np.random.default_rng(0)
    batch = batch_per_device * n_dev
    x = rng.normal(0, 1, (batch, img, img, 3)).astype("float32")
    y = np.eye(n_classes, dtype="float32")[
        rng.integers(0, n_classes, batch)]

    net.set_listeners(PerformanceListener(frequency=2))
    pw = ParallelWrapper(net, mesh, prefetch_buffer=2)
    pw.fit(ListDataSetIterator([DataSet(x, y)] * steps), epochs=1)
    print(f"final loss {float(net.score_value):.4f} after "
          f"{net.iteration_count} steps")


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--img", type=int, default=64)
    p.add_argument("--steps", type=int, default=10)
    args = p.parse_args()
    main(img=args.img, steps=args.steps)
