"""LeNet on MNIST — the framework's hello-world.

Mirrors the reference's canonical LeNet example: config DSL →
MultiLayerNetwork → fit with listeners → evaluate → checkpoint →
reload. Uses real MNIST if cached locally, a deterministic synthetic
surrogate otherwise.

Run: python examples/lenet_mnist.py [--epochs 3] [--batch 128]
"""

import os
import sys

# allow running straight from a repo checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from deeplearning4j_tpu.util.platform import pin_cpu_platform

pin_cpu_platform()   # dead TPU tunnel must not hang CPU-pinned runs

import argparse

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.data.fetchers import MnistDataSetIterator
from deeplearning4j_tpu.data.iterators import AsyncDataSetIterator
from deeplearning4j_tpu.nn.conf import updaters
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (ConvolutionLayer, DenseLayer,
                                               OutputLayer,
                                               SubsamplingLayer)
from deeplearning4j_tpu.train.listeners import (PerformanceListener,
                                                ScoreIterationListener)
from deeplearning4j_tpu.util.model_serializer import (restore_model,
                                                      write_model)


def main(epochs=3, batch=128, n_train=4096, out="/tmp/lenet.zip"):
    conf = (NeuralNetConfiguration.builder()
            .set_seed(12345)
            .updater(updaters.adam(2e-3))
            .list()
            .layer(ConvolutionLayer(n_out=20, kernel=(5, 5),
                                    activation="relu"))
            .layer(SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=50, kernel=(5, 5),
                                    activation="relu"))
            .layer(SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=500, activation="relu"))
            .layer(OutputLayer(n_out=10, loss="mcxent"))
            .set_input_type(InputType.convolutional_flat(28, 28, 1))
            .build())

    net = MultiLayerNetwork(conf).init()
    print(net.summary())
    net.set_listeners(ScoreIterationListener(10),
                      PerformanceListener(frequency=10))

    train = AsyncDataSetIterator(
        MnistDataSetIterator(batch, train=True, n=n_train))
    test = MnistDataSetIterator(256, train=False, n=1024, shuffle=False)

    net.fit(train, epochs=epochs)
    ev = net.evaluate(test)
    print(ev.stats())

    write_model(net, out)
    reloaded = restore_model(out)
    print(f"checkpoint round trip OK: "
          f"{reloaded.evaluate(test).accuracy():.4f} accuracy")
    return ev.accuracy()


if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch", type=int, default=128)
    args = p.parse_args()
    main(args.epochs, args.batch)
