"""Import a Keras model and fine-tune it with transfer learning.

Mirrors the reference's modelimport + transfer-learning workflow:
KerasModelImport → freeze feature extractor → replace head → fit.
Builds a small Keras model on the fly (keras must be installed) so the
example is self-contained.

Run: python examples/keras_import_finetune.py
"""

import os
import sys

# allow running straight from a repo checkout
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from deeplearning4j_tpu.util.platform import pin_cpu_platform

pin_cpu_platform()   # dead TPU tunnel must not hang CPU-pinned runs

import numpy as np


def main(h5_path="/tmp/keras_base.h5"):
    os.environ.setdefault("KERAS_BACKEND", "tensorflow")
    import keras
    from keras import layers

    # 1. a "pretrained" Keras model
    km = keras.Sequential([
        keras.Input((4,)),
        layers.Dense(16, activation="relu"),
        layers.Dense(8, activation="relu"),
        layers.Dense(3, activation="softmax"),
    ])
    km.save(h5_path)

    # 2. import
    from deeplearning4j_tpu.keras import import_keras_model_and_weights
    net = import_keras_model_and_weights(h5_path)
    print("imported:")
    print(net.summary())

    # 3. verify parity with Keras on the same inputs
    x = np.random.default_rng(0).normal(0, 1, (4, 4)).astype("float32")
    diff = np.abs(km.predict(x, verbose=0)
                  - np.asarray(net.output(x))).max()
    print(f"max |keras - ours| = {diff:.2e}")

    # 4. freeze the feature extractor, new head, fine-tune
    from deeplearning4j_tpu.data.fetchers import iris_data
    from deeplearning4j_tpu.nn.conf import updaters
    from deeplearning4j_tpu.nn.conf.layers import OutputLayer
    from deeplearning4j_tpu.nn.transfer_learning import (
        FineTuneConfiguration, TransferLearning)
    tuned = (TransferLearning.builder(net)
             .fine_tune_configuration(
                 FineTuneConfiguration(updater=updaters.adam(0.02)))
             .set_feature_extractor(1)
             .remove_output_layer()
             .add_layer(OutputLayer(n_out=3))
             .build())
    xs, ys = iris_data()
    tuned.fit(xs[:120], ys[:120], epochs=30, batch_size=32)
    acc = tuned.evaluate(xs[120:], ys[120:]).accuracy()
    print(f"fine-tuned accuracy: {acc:.3f}")
    return acc


if __name__ == "__main__":
    main()
