"""Streaming decode + autoregressive generation — round-5 features
end to end.

A small character LM (embedding + causal transformer blocks) trains
briefly, then generates text two ways and checks they agree:

1. the eager ``rnn_time_step`` path (reference rnnTimeStep contract,
   MultiLayerNetwork.java:2656 — concat-grown KV cache, a Python
   dispatch per token);
2. the TPU-first ``streaming_session``: fixed-capacity KV caches
   updated in place, ONE compiled executable per chunk length, and
   ``generate()`` sampling on device arrays with no per-token host
   sync.

Run: python examples/streaming_generation.py [--epochs 3]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from deeplearning4j_tpu.util.platform import pin_cpu_platform

pin_cpu_platform()   # dead TPU tunnel must not hang CPU-pinned runs

import numpy as np

TEXT = ("the quick brown fox jumps over the lazy dog and the cat "
        "sat on the mat while the dog ran in the park ") * 40


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq-len", type=int, default=32)
    ap.add_argument("--gen-tokens", type=int, default=24)
    args = ap.parse_args()

    from deeplearning4j_tpu import (MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import updaters
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (
        EmbeddingSequenceLayer, RnnOutputLayer,
        TransformerEncoderLayer)

    chars = sorted(set(TEXT))
    V = len(chars)
    idx = {c: i for i, c in enumerate(chars)}
    ids = np.array([idx[c] for c in TEXT], np.int32)
    T = args.seq_len

    conf = (NeuralNetConfiguration.builder().set_seed(7)
            .updater(updaters.adam(3e-3)).list()
            .layer(EmbeddingSequenceLayer(n_in=V, n_out=32))
            .layer(TransformerEncoderLayer(n_heads=4, causal=True))
            .layer(TransformerEncoderLayer(n_heads=4, causal=True))
            .layer(RnnOutputLayer(n_out=V, loss="mcxent"))
            .set_input_type(InputType.recurrent(V, T)).build())
    net = MultiLayerNetwork(conf).init()

    # next-char batches
    rng = np.random.default_rng(0)
    starts = rng.integers(0, len(ids) - T - 1, 256)
    x = np.stack([ids[s:s + T] for s in starts]).astype(np.float32)
    y = np.eye(V, dtype=np.float32)[
        np.stack([ids[s + 1:s + T + 1] for s in starts])]
    for epoch in range(args.epochs):
        for b in range(0, len(x), args.batch):
            net.fit(DataSet(x[b:b + args.batch], y[b:b + args.batch]))
        print(f"epoch {epoch}: loss {float(net.score_value):.4f}")

    prompt_txt = "the quick"
    prompt = np.array([[idx[c] for c in prompt_txt]], np.int32)
    n = args.gen_tokens
    cap = prompt.shape[1] + n

    # 1. TPU-first: bounded session + device-side greedy sampling
    # (step-by-step here so per-step probabilities are observable;
    # sess.generate(prompt, n) / generate(..., fused=True) wrap the
    # same loop in one call / one XLA program)
    sess = net.streaming_session(capacity=cap, batch=1)
    p = np.asarray(sess.step(prompt[:, :, None].astype(np.float32)))
    last = p[:, -1]
    gen, probs_fast = [], []
    for _ in range(n):
        probs_fast.append(last[0])
        nxt = last.argmax(axis=-1)
        gen.append(int(nxt[0]))
        last = np.asarray(sess.step(
            nxt[:, None, None].astype(np.float32)))[:, 0]
    text_fast = "".join(chars[i] for i in gen)

    # fused: the whole decode as ONE XLA program — same computation
    # path as the stepped loop, so ids match exactly
    sess.reset()
    ids_f = np.asarray(sess.generate(prompt, n, fused=True))[0]
    assert list(ids_f) == gen, "fused generate diverged"
    print("fused single-program generate matches stepped loop OK")

    # 2. eager reference: rnn_time_step + host argmax per token
    net.rnn_clear_previous_state()
    probs = np.asarray(net.rnn_time_step(
        prompt[:, :, None].astype(np.float32)))
    last = probs[:, -1]
    out, probs_eager = [], []
    for _ in range(n):
        probs_eager.append(last[0])
        nxt = last.argmax(axis=-1)
        out.append(int(nxt[0]))
        last = np.asarray(net.rnn_time_step(
            nxt[:, None, None].astype(np.float32)))[:, 0]
    text_eager = "".join(chars[i] for i in out)

    print(f"prompt: {prompt_txt!r}")
    print(f"generated (bounded session): {text_fast!r}")
    print(f"generated (eager reference): {text_eager!r}")
    # the two paths reduce attention in different orders; a near-tied
    # argmax may legitimately flip one character and diverge after it,
    # so the asserted contract is the per-step probabilities up to the
    # first divergence, not a 24-token exact id chain
    if text_fast != text_eager:
        k = next(i for i, (a, b) in
                 enumerate(zip(text_fast, text_eager)) if a != b)
        np.testing.assert_allclose(probs_fast[k], probs_eager[k],
                                   atol=1e-4)
        print(f"paths diverged at a float-tied step {k} "
              "(probabilities equal to 1e-4) — OK")
    print("bounded session matches eager decode OK")
    print(f"compiled executables: "
          f"{sorted(sess._step_cache)} (prefill + decode)")


if __name__ == "__main__":
    main()
