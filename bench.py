"""Benchmark harness — prints ONE JSON line with the headline metric.

Headline (BASELINE.json north star): ResNet50 training throughput,
images/sec/chip, vs a hand-written JAX/Flax ResNet50 train step run in
the same process on the same chip (``vs_baseline`` = ours/flax; 1.0 =
parity with idiomatic flax, the reference implementation the target is
defined against).

Extra metrics (LeNet throughput) print to stderr for debugging; stdout
stays one JSON line for the driver.
"""

import json
import sys
import time

import numpy as np

BATCH = 128
IMG = 224
STEPS = 40
WARMUP = 5


def _time_steps(step_fn, args, steps, warmup, get_loss):
    import jax
    for _ in range(warmup):
        args = step_fn(*args)
    jax.block_until_ready(get_loss(args))
    t0 = time.perf_counter()
    for _ in range(steps):
        args = step_fn(*args)
    jax.block_until_ready(get_loss(args))
    return time.perf_counter() - t0


def bench_ours(batch=BATCH, img=IMG, steps=STEPS):
    import jax
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import updaters
    from deeplearning4j_tpu.zoo import ResNet50

    net = ResNet50(n_classes=1000, input_shape=(img, img, 3),
                   updater=updaters.nesterovs(0.1, 0.9)).init()
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (batch, img, img, 3)).astype("float32")
    y = np.eye(1000, dtype="float32")[rng.integers(0, 1000, batch)]
    batch_t = net._batch_tuple(net._as_multi(DataSet(x, y)))
    step = net._make_train_step()
    key = jax.random.PRNGKey(0)
    it = np.int32(0)

    def one(params, state, opt, loss):
        return step(params, state, opt, batch_t, key, it)

    dt = _time_steps(one, (net.params, net.state, net.opt_state, None),
                     steps, WARMUP, lambda a: a[3])
    return steps * batch / dt


def bench_flax_resnet50(batch=BATCH, img=IMG, steps=STEPS):
    import jax
    import jax.numpy as jnp
    import optax
    from flax import linen as nn

    class Bottleneck(nn.Module):
        mid: int
        out: int
        stride: int = 1
        project: bool = False

        @nn.compact
        def __call__(self, x, train=True):
            r = x
            y = nn.Conv(self.mid, (1, 1), (self.stride, self.stride),
                        use_bias=False)(x)
            y = nn.relu(nn.BatchNorm(use_running_average=not train)(y))
            y = nn.Conv(self.mid, (3, 3), padding="SAME",
                        use_bias=False)(y)
            y = nn.relu(nn.BatchNorm(use_running_average=not train)(y))
            y = nn.Conv(self.out, (1, 1), use_bias=False)(y)
            y = nn.BatchNorm(use_running_average=not train)(y)
            if self.project:
                r = nn.Conv(self.out, (1, 1), (self.stride, self.stride),
                            use_bias=False)(x)
                r = nn.BatchNorm(use_running_average=not train)(r)
            return nn.relu(y + r)

    class ResNet50F(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = nn.Conv(64, (7, 7), (2, 2), padding="SAME",
                        use_bias=False)(x)
            x = nn.relu(nn.BatchNorm(use_running_average=not train)(x))
            x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
            for blocks, mid, out, stride in ((3, 64, 256, 1),
                                             (4, 128, 512, 2),
                                             (6, 256, 1024, 2),
                                             (3, 512, 2048, 2)):
                for b in range(blocks):
                    x = Bottleneck(mid, out,
                                   stride if b == 0 else 1,
                                   project=(b == 0))(x, train)
            x = jnp.mean(x, axis=(1, 2))
            return nn.Dense(1000)(x)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(0, 1, (batch, img, img, 3))
                    .astype("float32"))
    y = jnp.asarray(np.eye(1000, dtype="float32")[
        rng.integers(0, 1000, batch)])
    model = ResNet50F()
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    params = variables["params"]
    batch_stats = variables["batch_stats"]
    tx = optax.sgd(0.1, momentum=0.9, nesterov=True)
    opt = tx.init(params)

    @jax.jit
    def step(params, batch_stats, opt, loss_prev):
        def loss_fn(p):
            logits, upd = model.apply(
                {"params": p, "batch_stats": batch_stats}, x, train=True,
                mutable=["batch_stats"])
            return optax.softmax_cross_entropy(logits, y).mean(), upd
        (loss, upd), g = jax.value_and_grad(loss_fn, has_aux=True)(params)
        u, opt2 = tx.update(g, opt, params)
        return optax.apply_updates(params, u), upd["batch_stats"], opt2, \
            loss

    dt = _time_steps(lambda *a: step(*a),
                     (params, batch_stats, opt, None), steps, WARMUP,
                     lambda a: a[3])
    return steps * batch / dt


def main():
    ours = bench_ours()
    print(f"ours: {ours:.1f} img/s", file=sys.stderr)
    ref = bench_flax_resnet50()
    print(f"flax ref: {ref:.1f} img/s", file=sys.stderr)
    print(json.dumps({
        "metric": "ResNet50 train throughput (batch 128, 224x224, f32)",
        "value": round(ours, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(ours / ref, 3),
    }))


if __name__ == "__main__":
    main()
