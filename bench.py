"""Benchmark harness — prints ONE JSON line with the headline metric.

Headline (BASELINE.json north star): ResNet50 training throughput,
images/sec/chip, vs a hand-written JAX/Flax ResNet50 train step run in
the same process on the same chip (``vs_baseline`` = ours/flax; 1.0 =
parity with idiomatic flax, the reference implementation the target is
defined against).

The FULL BASELINE.md config list also runs (LeNet/MNIST train,
GravesLSTM char-RNN train vs a hand-written flax/optax ``nn.scan``
baseline, Keras-imported VGG16 inference vs hand-written flax VGG16)
and is written to ``BENCH_DETAIL.json`` + echoed to stderr; stdout
stays one JSON line for the driver. MFU is reported for the
matmul/conv-dominated configs (model FLOPs / wall-clock / bf16 peak of
the detected chip).

Skip the non-headline configs with ``--headline-only`` (or env
BENCH_HEADLINE_ONLY=1) when iterating.

Delivery contract (round-5): a watchdog guarantees ONE stdout JSON
line and exit code 0 before a hard internal deadline under
BENCH_BUDGET_SECONDS, whatever the tunnel does — freshly measured if
the headline leg finished, else the last committed BENCH_DETAIL
headline tagged ``"stale": true``. Rehearse the degraded-tunnel paths
with BENCH_REHEARSE_HANG=1 (legs hang) or BENCH_REHEARSE_ORCH_HANG=1
(orchestrator wedges); see tests/test_bench_harness.py.
"""

import json
import os
import signal
import sys
import threading
import time

import numpy as np

BATCH = 128
IMG = 224
STEPS = 40
WARMUP = 5
LENET_BATCH = 128
LENET_STEPS = 600

# bf16 peak FLOP/s per chip by device kind (prefix match). Used only
# for the MFU side-metric; throughput vs flax is the headline. Kept
# as a local mirror of observability/step_profile.py's table: the
# orchestrator must stay import-free of the package (and of jax)
# until its watchdog is armed.
_PEAK_BF16 = {
    "TPU v5 lite": 197e12,    # v5e
    "TPU v5": 459e12,         # v5p
    "TPU v4": 275e12,
    "TPU v6": 918e12,
}


def _peak_for_kind(kind):
    for prefix, peak in sorted(_PEAK_BF16.items(),
                               key=lambda kv: -len(kv[0])):
        if kind.startswith(prefix):
            return peak
    return None


def _peak_flops():
    import jax
    kind = jax.devices()[0].device_kind
    return _peak_for_kind(kind), kind


def _make_measure(step_fn, args, steps, warmup, get_loss):
    """Compile + warm up now; return a zero-arg measure() giving the
    wall time of one ``steps``-burst. Measurement discipline for the
    tunnel'd chip: (a) bursts are LARGE (seconds of compute) so the
    tunnel's fixed ~130 ms per-burst sync cost is a few percent — and
    it lands on ours and baseline equally, so the ratio is unbiased;
    (b) noise here is additive-positive (sync cost, drift, host
    contention), so the caller takes the MIN of N interleaved bursts —
    the robust estimator. (Two-point subtraction of burst pairs was
    tried and rejected: subtracting makes the noise signed, and under
    heavy drift the difference can even go negative.)"""
    import jax
    import jax.numpy as jnp
    for _ in range(warmup):
        args = step_fn(*args)
    float(jnp.sum(get_loss(args)))
    holder = {"args": args}

    def measure() -> float:
        a = holder["args"]
        t0 = time.perf_counter()
        for _ in range(steps):
            a = step_fn(*a)
        # host FETCH, not block_until_ready: the tunnel's block is a
        # no-op for non-donated arrays (see _time_infer note); a fetch
        # of the loss scalar is the only reliable end-of-burst sync
        float(jnp.sum(get_loss(a)))
        holder["args"] = a
        return time.perf_counter() - t0

    return measure


def _interleave(measure_ours, measure_ref, repeats=3):
    """Best-of-N with alternating bursts: (ours_dt, ref_dt)."""
    best_o = best_r = float("inf")
    for _ in range(max(1, repeats)):
        best_o = min(best_o, measure_ours())
        best_r = min(best_r, measure_ref())
    return best_o, best_r


def _time_infer(fn, x, steps, warmup):
    """Inference timing with a data dependency chaining step N+1 on
    step N's output — the tunnel'd runtime dedupes identical in-flight
    calls, which times as ~0. Large single bursts + caller min-of-N
    (see _make_measure's noise note). ``chained`` is deliberately NOT
    jitted: fn may close over big weights, and a jit here would bake
    them into the HLO as constants (see bench_flax_vgg16_infer); the
    tiny select runs as a second dispatch instead."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def _link(out, x):
        # the tunnel'd runtime memoizes (executable, input CONTENT):
        # returning x bitwise-identical made all 60 steps one cached
        # call (implied MFU 50+). The next input must (a) depend on
        # this step's output — isnan is runtime-only, uncomputable at
        # compile — and (b) actually drift: +1e-4/step is irrelevant
        # to N(0,1) image stats but defeats content-keyed caching.
        bump = jnp.where(jnp.isnan(jnp.mean(out)),
                         jnp.asarray(2e-4, x.dtype),
                         jnp.asarray(1e-4, x.dtype))
        return x + bump

    def chained(x):
        out = fn(x)
        return _link(out, x), out

    xx = jnp.asarray(x)
    for _ in range(warmup):
        xx, out = chained(xx)
    float(jnp.sum(out))

    t0 = time.perf_counter()
    a = xx
    for _ in range(steps):
        a, out = chained(a)
    # block_until_ready is a no-op for non-donated arrays through the
    # tunnel (training steps donate, which forces real backpressure;
    # inference doesn't) — a host FETCH is the only reliable sync
    float(jnp.sum(out))
    return time.perf_counter() - t0


# ---------------------------------------------------------------------------
# 1. ResNet50 training (headline)
# ---------------------------------------------------------------------------

def bench_ours(batch=BATCH, img=IMG, steps=STEPS, prep=False):
    import jax
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import updaters
    from deeplearning4j_tpu.zoo import ResNet50

    net = ResNet50(n_classes=1000, input_shape=(img, img, 3),
                   updater=updaters.nesterovs(0.1, 0.9)).init()
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (batch, img, img, 3)).astype("float32")
    y = np.eye(1000, dtype="float32")[rng.integers(0, 1000, batch)]
    batch_t = net._batch_tuple(net._as_multi(DataSet(x, y)))
    step = net._make_train_step()
    key = jax.random.PRNGKey(0)
    it = np.int32(0)

    def one(params, state, opt, loss):
        return step(params, state, opt, batch_t, key, it)

    m = _make_measure(one, (net.params, net.state, net.opt_state, None),
                      steps, WARMUP, lambda a: a[3])
    if prep:
        return m
    return steps * batch / m()


def bench_flax_resnet50(batch=BATCH, img=IMG, steps=STEPS, prep=False,
                        dtype=None):
    import jax
    import jax.numpy as jnp
    import optax
    from flax import linen as nn

    dt = dtype or jnp.float32

    class Bottleneck(nn.Module):
        mid: int
        out: int
        stride: int = 1
        project: bool = False

        @nn.compact
        def __call__(self, x, train=True):
            r = x
            y = nn.Conv(self.mid, (1, 1), (self.stride, self.stride),
                        use_bias=False, dtype=dt)(x)
            y = nn.relu(nn.BatchNorm(use_running_average=not train)(y))
            y = nn.Conv(self.mid, (3, 3), padding="SAME",
                        use_bias=False, dtype=dt)(y)
            y = nn.relu(nn.BatchNorm(use_running_average=not train)(y))
            y = nn.Conv(self.out, (1, 1), use_bias=False, dtype=dt)(y)
            y = nn.BatchNorm(use_running_average=not train)(y)
            if self.project:
                r = nn.Conv(self.out, (1, 1), (self.stride, self.stride),
                            use_bias=False, dtype=dt)(x)
                r = nn.BatchNorm(use_running_average=not train)(r)
            return nn.relu(y + r)

    class ResNet50F(nn.Module):
        @nn.compact
        def __call__(self, x, train=True):
            x = nn.Conv(64, (7, 7), (2, 2), padding="SAME",
                        use_bias=False, dtype=dt)(x)
            x = nn.relu(nn.BatchNorm(use_running_average=not train)(x))
            x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")
            for blocks, mid, out, stride in ((3, 64, 256, 1),
                                             (4, 128, 512, 2),
                                             (6, 256, 1024, 2),
                                             (3, 512, 2048, 2)):
                for b in range(blocks):
                    x = Bottleneck(mid, out,
                                   stride if b == 0 else 1,
                                   project=(b == 0))(x, train)
            x = jnp.mean(x, axis=(1, 2))
            return nn.Dense(1000)(x)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(0, 1, (batch, img, img, 3))
                    .astype("float32"))
    y = jnp.asarray(np.eye(1000, dtype="float32")[
        rng.integers(0, 1000, batch)])
    model = ResNet50F()
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    params = variables["params"]
    batch_stats = variables["batch_stats"]
    tx = optax.sgd(0.1, momentum=0.9, nesterov=True)
    opt = tx.init(params)

    @jax.jit
    def step(params, batch_stats, opt, loss_prev):
        def loss_fn(p):
            logits, upd = model.apply(
                {"params": p, "batch_stats": batch_stats}, x, train=True,
                mutable=["batch_stats"])
            return optax.softmax_cross_entropy(logits, y).mean(), upd
        (loss, upd), g = jax.value_and_grad(loss_fn, has_aux=True)(params)
        u, opt2 = tx.update(g, opt, params)
        return optax.apply_updates(params, u), upd["batch_stats"], opt2, \
            loss

    m = _make_measure(lambda *a: step(*a),
                      (params, batch_stats, opt, None), steps, WARMUP,
                      lambda a: a[3])
    if prep:
        return m
    return steps * batch / m()


def bench_flax_resnet50_bf16(batch=BATCH, img=IMG, steps=STEPS,
                             prep=False):
    import jax.numpy as jnp
    return bench_flax_resnet50(batch, img, steps, prep,
                               dtype=jnp.bfloat16)


# ---------------------------------------------------------------------------
# 2. LeNet / MNIST training (BASELINE.md item 1)
# ---------------------------------------------------------------------------

def bench_ours_lenet(batch=LENET_BATCH, steps=LENET_STEPS,
                     prep=False):
    import jax
    from deeplearning4j_tpu import (MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import updaters
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (ConvolutionLayer,
                                                   DenseLayer,
                                                   OutputLayer,
                                                   SubsamplingLayer)

    conf = (NeuralNetConfiguration.builder().set_seed(0)
            .updater(updaters.adam(1e-3)).list()
            .layer(ConvolutionLayer(n_out=20, kernel=(5, 5),
                                    activation="relu"))
            .layer(SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=50, kernel=(5, 5),
                                    activation="relu"))
            .layer(SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=500, activation="relu"))
            .layer(OutputLayer(n_out=10, loss="mcxent"))
            .set_input_type(InputType.convolutional_flat(28, 28, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (batch, 784)).astype("float32")
    y = np.eye(10, dtype="float32")[rng.integers(0, 10, batch)]
    batch_t = net._batch_tuple(DataSet(x, y))
    step = net._make_train_step()
    key = jax.random.PRNGKey(0)
    it = np.int32(0)

    def one(params, state, opt, loss):
        return step(params, state, opt, batch_t, key, it)

    m = _make_measure(one, (net.params, net.state, net.opt_state, None),
                      steps, WARMUP, lambda a: a[3])
    if prep:
        return m
    return steps * batch / min(m() for _ in range(3))


def bench_flax_lenet(batch=LENET_BATCH, steps=LENET_STEPS,
                     prep=False):
    import jax
    import jax.numpy as jnp
    import optax
    from flax import linen as nn

    class LeNet(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = x.reshape((x.shape[0], 28, 28, 1))
            x = nn.relu(nn.Conv(20, (5, 5), padding="VALID")(x))
            x = nn.max_pool(x, (2, 2), (2, 2))
            x = nn.relu(nn.Conv(50, (5, 5), padding="VALID")(x))
            x = nn.max_pool(x, (2, 2), (2, 2))
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(500)(x))
            return nn.Dense(10)(x)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(0, 1, (batch, 784)).astype("float32"))
    y = jnp.asarray(np.eye(10, dtype="float32")[
        rng.integers(0, 10, batch)])
    model = LeNet()
    params = model.init(jax.random.PRNGKey(0), x)["params"]
    tx = optax.adam(1e-3)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, loss_prev):
        def loss_fn(p):
            logits = model.apply({"params": p}, x)
            return optax.softmax_cross_entropy(logits, y).mean()
        loss, g = jax.value_and_grad(loss_fn)(params)
        u, opt2 = tx.update(g, opt, params)
        return optax.apply_updates(params, u), opt2, loss

    m = _make_measure(lambda *a: step(*a), (params, opt, None), steps,
                      WARMUP, lambda a: a[2])
    if prep:
        return m
    return steps * batch / min(m() for _ in range(3))


# ---------------------------------------------------------------------------
# 3. GravesLSTM char-RNN training (BASELINE.md item 3 — the lax.scan
#    path the reference accelerates with CudnnLSTMHelper)
# ---------------------------------------------------------------------------

CHAR_BATCH = 32
CHAR_T = 64
CHAR_VOCAB = 80
CHAR_HIDDEN = 256
CHAR_STEPS = 300


def bench_ours_char_rnn(batch=CHAR_BATCH, t=CHAR_T, vocab=CHAR_VOCAB,
                        hidden=CHAR_HIDDEN, steps=CHAR_STEPS,
                        prep=False):
    import jax
    from deeplearning4j_tpu import (MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import updaters
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (GravesLSTM,
                                                   RnnOutputLayer)

    conf = (NeuralNetConfiguration.builder().set_seed(0)
            .updater(updaters.rmsprop(1e-3)).list()
            .layer(GravesLSTM(n_out=hidden, activation="tanh"))
            .layer(GravesLSTM(n_out=hidden, activation="tanh"))
            .layer(RnnOutputLayer(n_out=vocab, loss="mcxent"))
            .set_input_type(InputType.recurrent(vocab, t)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, (batch, t))
    x = np.eye(vocab, dtype="float32")[ids]
    y = np.eye(vocab, dtype="float32")[np.roll(ids, -1, axis=1)]
    batch_t = net._batch_tuple(DataSet(x, y))
    step = net._make_train_step()
    key = jax.random.PRNGKey(0)
    it = np.int32(0)

    def one(params, state, opt, loss):
        return step(params, state, opt, batch_t, key, it)

    m = _make_measure(one, (net.params, net.state, net.opt_state, None),
                      steps, WARMUP, lambda a: a[3])
    if prep:
        return m
    # chars (timesteps) per second
    return steps * batch * t / min(m() for _ in range(3))


def bench_flax_char_rnn(batch=CHAR_BATCH, t=CHAR_T, vocab=CHAR_VOCAB,
                        hidden=CHAR_HIDDEN, steps=CHAR_STEPS,
                        prep=False):
    """Hand-written flax/optax baseline: nn.scan over OptimizedLSTMCell
    ×2 + per-step softmax head — the idiomatic JAX char-RNN."""
    import jax
    import jax.numpy as jnp
    import optax
    from flax import linen as nn

    class CharRNN(nn.Module):
        @nn.compact
        def __call__(self, x):
            for i in range(2):
                x = nn.RNN(nn.OptimizedLSTMCell(hidden),
                           name=f"lstm{i}")(x)
            return nn.Dense(vocab)(x)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, (batch, t))
    x = jnp.asarray(np.eye(vocab, dtype="float32")[ids])
    y = jnp.asarray(np.eye(vocab, dtype="float32")[
        np.roll(ids, -1, axis=1)])
    model = CharRNN()
    params = model.init(jax.random.PRNGKey(0), x)["params"]
    tx = optax.rmsprop(1e-3)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, loss_prev):
        def loss_fn(p):
            logits = model.apply({"params": p}, x)
            return optax.softmax_cross_entropy(logits, y).mean()
        loss, g = jax.value_and_grad(loss_fn)(params)
        u, opt2 = tx.update(g, opt, params)
        return optax.apply_updates(params, u), opt2, loss

    m = _make_measure(lambda *a: step(*a), (params, opt, None), steps,
                      WARMUP, lambda a: a[2])
    if prep:
        return m
    return steps * batch * t / min(m() for _ in range(3))


# ---------------------------------------------------------------------------
# 4. Keras-imported VGG16 inference (BASELINE.md item 4)
# ---------------------------------------------------------------------------

VGG_BATCH = 32
VGG_STEPS = 60


_KERAS_VGG16_SCRIPT = r"""
import sys
import keras
from keras import layers
model = keras.Sequential(name="vgg16")
model.add(keras.Input((224, 224, 3)))
for block, (n, reps) in enumerate((
        (64, 2), (128, 2), (256, 3), (512, 3), (512, 3))):
    for r in range(reps):
        model.add(layers.Conv2D(n, 3, padding="same", activation="relu",
                                name=f"b{block}c{r}"))
    model.add(layers.MaxPooling2D(2, 2, name=f"b{block}p"))
model.add(layers.Flatten(name="flat"))
model.add(layers.Dense(4096, activation="relu", name="fc1"))
model.add(layers.Dense(4096, activation="relu", name="fc2"))
model.add(layers.Dense(1000, activation="softmax", name="pred"))
model.save(sys.argv[1])
"""


def _build_keras_vgg16(path):
    """Random-weight VGG16 saved in legacy h5 (no egress). Runs keras
    in a SUBPROCESS: importing TF into a process whose JAX already
    initialized the TPU deadlocks the h5 save."""
    import subprocess
    subprocess.run([sys.executable, "-c", _KERAS_VGG16_SCRIPT, path],
                   check=True, timeout=240,
                   env={**os.environ, "JAX_PLATFORMS": "cpu",
                        "CUDA_VISIBLE_DEVICES": ""})


def bench_keras_imported_vgg16(batch=VGG_BATCH, steps=VGG_STEPS,
                               prep=False):
    import jax

    from deeplearning4j_tpu.keras.importer import (
        import_keras_model_and_weights)

    import importlib.util
    if (importlib.util.find_spec("keras") is None
            or importlib.util.find_spec("h5py") is None):
        # clean dependency skip (rc 3 in leg mode), not a retryable
        # failure: the build subprocess would die with
        # CalledProcessError otherwise and burn a cooldown + retry
        raise ImportError("keras/h5py not installed")
    # cache the 554MB generated h5 across runs — the keras-subprocess
    # build is ~2 min of the leg and identical every time
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".bench_cache")
    os.makedirs(cache_dir, exist_ok=True)
    path = os.path.join(cache_dir, "vgg16.h5")
    if not os.path.exists(path):
        # keras validates the extension, so the temp name must end .h5
        tmp = os.path.join(cache_dir, "vgg16.build-tmp.h5")
        _build_keras_vgg16(tmp)
        os.replace(tmp, path)
    net = import_keras_model_and_weights(path)
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (batch, 224, 224, 3)).astype("float32")
    out0 = net.output(x)            # builds + caches the jit
    jax.block_until_ready(out0)

    def m():
        return _time_infer(net.output, x, steps, 1)
    if prep:
        return m
    return steps * batch / m()


def bench_flax_vgg16_infer(batch=VGG_BATCH, steps=VGG_STEPS,
                           prep=False):
    import jax
    import jax.numpy as jnp
    from flax import linen as nn

    class VGG16F(nn.Module):
        @nn.compact
        def __call__(self, x):
            for n, reps in ((64, 2), (128, 2), (256, 3), (512, 3),
                            (512, 3)):
                for _ in range(reps):
                    x = nn.relu(nn.Conv(n, (3, 3), padding="SAME")(x))
                x = nn.max_pool(x, (2, 2), (2, 2))
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(4096)(x))
            x = nn.relu(nn.Dense(4096)(x))
            return nn.softmax(nn.Dense(1000)(x))

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(0, 1, (batch, 224, 224, 3))
                    .astype("float32"))
    model = VGG16F()
    params = model.init(jax.random.PRNGKey(0), x)
    # params as an ARGUMENT, never a closure: closed-over arrays bake
    # into the HLO as literals, and VGG16's 554MB of constants breaks
    # the tunnel's compile endpoint (the recurring remote_compile
    # broken-pipe — our side passes params as args and never failed)
    infer = jax.jit(model.apply)

    def fn(x):
        return infer(params, x)

    def m():
        return _time_infer(fn, x, steps, 1)
    if prep:
        return m
    return steps * batch / m()


# ---------------------------------------------------------------------------
# analytic model FLOPs for MFU
# ---------------------------------------------------------------------------

RESNET50_FWD_FLOPS = 4.09e9        # per 224x224 image (2*MACs)
VGG16_FWD_FLOPS = 15.47e9
LENET_FWD_FLOPS = 4.6e6
# GravesLSTM step: 4 gates × (in+hidden+peep) ≈ 2*4*h*(in+h) MACs/cell
_CH = CHAR_HIDDEN
CHAR_RNN_FWD_FLOPS_PER_CHAR = (
    2 * 4 * _CH * (CHAR_VOCAB + _CH)          # layer 1
    + 2 * 4 * _CH * (_CH + _CH)               # layer 2
    + 2 * _CH * CHAR_VOCAB)                   # head
TRAIN_MULT = 3.0                    # bwd ≈ 2× fwd


def _mfu(per_item_fwd_flops, items_per_sec, train, peak):
    if peak is None:
        return None
    flops = per_item_fwd_flops * (TRAIN_MULT if train else 1.0)
    return items_per_sec * flops / peak



# ---------------------------------------------------------------------------
# legs — each returns one BENCH_DETAIL config dict. Legs run in their
# own SUBPROCESS (``--leg NAME``): the tunnel'd TPU terminal degrades
# inside long-lived processes (observed: remote_compile broken-pipe and
# async-no-block timings after ~30 min), so each leg gets a fresh
# connection and its own timeout, and a crashed leg cannot take the
# others down. The persistent XLA cache keeps repeat compiles fast.
# ---------------------------------------------------------------------------

def _check_plausible(mfu_like, what):
    """A degraded tunnel sometimes stops blocking on results and legs
     'measure' physically impossible throughput. Reject anything that
    implies >90% of peak so the orchestrator can retry the leg."""
    if mfu_like is not None and mfu_like > 0.9:
        raise RuntimeError(
            f"implausible timing for {what}: implied MFU "
            f"{mfu_like:.2f} — tunnel degraded (non-blocking sync?)")


BURST_STEPS = 10


def _leg_resnet_burst(peak):
    """Degraded-tunnel FRESH path (round-5 verdict next #1a): a
    <=10-timed-step burst of the headline config, run FIRST and
    committed before the full legs start. Once the persistent XLA
    cache holds the two executables this is seconds of device time —
    an honest freshly-measured headline even when the 420s full leg
    cannot finish through a degraded tunnel. The full leg, when it
    completes, supersedes this number on stdout; the burst stays in
    BENCH_DETAIL tagged ``"burst": true``."""
    m_ours = bench_ours(steps=BURST_STEPS, prep=True)
    m_ref = bench_flax_resnet50(steps=BURST_STEPS, prep=True)
    dt_o, dt_r = _interleave(m_ours, m_ref, repeats=2)
    ours = BURST_STEPS * BATCH / dt_o
    ref = BURST_STEPS * BATCH / dt_r
    print(f"resnet50 BURST ours: {ours:.1f} img/s, flax ref: "
          f"{ref:.1f}", file=sys.stderr)
    if peak:
        _check_plausible(_mfu(RESNET50_FWD_FLOPS, max(ours, ref), True,
                              peak), "resnet50 f32 burst")
    return {
        "metric": ("ResNet50 train throughput (batch 128, 224x224, "
                   f"f32, {BURST_STEPS}-step burst)"),
        "value": round(ours, 1), "unit": "images/sec/chip",
        "baseline": round(ref, 1), "vs_baseline": round(ours / ref, 3),
        "burst": True,
        "mfu": round(_mfu(RESNET50_FWD_FLOPS, ours, True, peak), 4)
        if peak else None,
        "note": ("short-burst fresh headline: committed before the "
                 "full legs so a degraded tunnel still yields a "
                 "freshly measured number; burst timing carries more "
                 "per-burst sync overhead than the full 40-step leg, "
                 "so the full leg's value supersedes it when both "
                 "land")}


def _leg_resnet_f32(peak):
    m_ours = bench_ours(prep=True)
    m_ref = bench_flax_resnet50(prep=True)
    dt_o, dt_r = _interleave(m_ours, m_ref, repeats=2)
    ours = STEPS * BATCH / dt_o
    ref = STEPS * BATCH / dt_r
    print(f"resnet50 ours: {ours:.1f} img/s, flax ref: {ref:.1f}",
          file=sys.stderr)
    if peak:
        _check_plausible(_mfu(RESNET50_FWD_FLOPS, max(ours, ref), True,
                              peak), "resnet50 f32")
    return {
        "metric": "ResNet50 train throughput (batch 128, 224x224, f32)",
        "value": round(ours, 1), "unit": "images/sec/chip",
        "baseline": round(ref, 1), "vs_baseline": round(ours / ref, 3),
        "mfu": round(_mfu(RESNET50_FWD_FLOPS, ours, True, peak), 4)
        if peak else None}


def _leg_resnet_bf16(peak):
    from deeplearning4j_tpu import dtypes
    with dtypes.policy_scope(dtypes.tpu_bf16()):
        m_ours = bench_ours(prep=True)
    m_ref = bench_flax_resnet50_bf16(prep=True)
    dt_o, dt_r = _interleave(m_ours, m_ref, repeats=3)
    ours16 = STEPS * BATCH / dt_o
    ref16 = STEPS * BATCH / dt_r
    print(f"resnet50 bf16 ours: {ours16:.1f} img/s, flax bf16: "
          f"{ref16:.1f}", file=sys.stderr)
    if peak:
        _check_plausible(_mfu(RESNET50_FWD_FLOPS, max(ours16, ref16),
                              True, peak), "resnet50 bf16")
    return {
        "metric": ("ResNet50 train throughput bf16 compute (batch "
                   "128, 224x224)"),
        "value": round(ours16, 1), "unit": "images/sec/chip",
        "baseline": round(ref16, 1),
        "vs_baseline": round(ours16 / ref16, 3),
        "mfu": round(_mfu(RESNET50_FWD_FLOPS, ours16, True, peak), 4)
        if peak else None,
        "note": ("ours: bf16 compute AND bf16 hidden activations "
                 "(f32 params/BN-stats/logits); baseline: flax "
                 "modules with dtype=bfloat16")}


def _leg_lenet(peak):
    m_ours = bench_ours_lenet(prep=True)
    m_ref = bench_flax_lenet(prep=True)
    # repeats=6: LeNet compute is ~1ms/step, so this leg times the
    # tunnel dispatch path, not the MXU — observed single-pair ratio
    # spread is 0.65-1.33x; more interleaved bursts tighten the min
    dt_o, dt_r = _interleave(m_ours, m_ref, repeats=6)
    lenet = LENET_STEPS * LENET_BATCH / dt_o
    lenet_ref = LENET_STEPS * LENET_BATCH / dt_r
    print(f"lenet ours: {lenet:.0f} img/s, flax: {lenet_ref:.0f}",
          file=sys.stderr)
    if peak:
        _check_plausible(_mfu(LENET_FWD_FLOPS, max(lenet, lenet_ref),
                              True, peak), "lenet")
    return {
        "metric": "LeNet MNIST train throughput (batch 128)",
        "value": round(lenet, 0), "unit": "images/sec/chip",
        "baseline": round(lenet_ref, 0),
        "vs_baseline": round(lenet / lenet_ref, 3),
        "mfu": round(_mfu(LENET_FWD_FLOPS, lenet, True, peak), 5)
        if peak else None,
        "note": ("dispatch-bound leg (~1 ms/step of compute): the "
                 "ratio carries the tunnel's dispatch jitter, "
                 "observed ±20% across runs on identical code")}


def _leg_char_rnn(peak):
    m_ours = bench_ours_char_rnn(prep=True)
    m_ref = bench_flax_char_rnn(prep=True)
    dt_o, dt_r = _interleave(m_ours, m_ref, repeats=3)
    chars = CHAR_STEPS * CHAR_BATCH * CHAR_T / dt_o
    chars_ref = CHAR_STEPS * CHAR_BATCH * CHAR_T / dt_r
    print(f"char-rnn ours: {chars:.0f} chars/s, flax scan: "
          f"{chars_ref:.0f}", file=sys.stderr)
    if peak:
        _check_plausible(_mfu(CHAR_RNN_FWD_FLOPS_PER_CHAR,
                              max(chars, chars_ref), True, peak),
                         "char-rnn")
    return {
        "metric": ("GravesLSTM char-RNN train throughput (batch "
                   f"{CHAR_BATCH}, T={CHAR_T}, 2x{CHAR_HIDDEN}, "
                   f"vocab {CHAR_VOCAB})"),
        "value": round(chars, 0), "unit": "chars/sec/chip",
        "baseline": round(chars_ref, 0),
        "vs_baseline": round(chars / chars_ref, 3),
        "mfu": round(_mfu(CHAR_RNN_FWD_FLOPS_PER_CHAR, chars, True,
                          peak), 5) if peak else None,
        "note": ("ours = GravesLSTM (peepholes: +25% gate FLOPs); "
                 "baseline = flax OptimizedLSTMCell nn.scan")}


def _leg_vgg16_import(peak):
    m_ours = bench_keras_imported_vgg16(prep=True)
    m_ref = bench_flax_vgg16_infer(prep=True)
    # repeats=3 (was 2): round-3 recorded 0.945x here; round-4 HLO
    # analysis showed ours and flax compile to IDENTICAL work (flops
    # 9.591e11, bytes 4.654e9, both to 4 digits), and 5 repeated runs
    # straddled parity (0.944-1.059) — the leg's ratio noise through
    # the tunnel is ~±6%, so take the min over more interleaved bursts
    dt_o, dt_r = _interleave(m_ours, m_ref, repeats=3)
    vgg = VGG_STEPS * VGG_BATCH / dt_o
    vgg_ref = VGG_STEPS * VGG_BATCH / dt_r
    print(f"vgg16 infer ours(keras-import): {vgg:.1f} img/s, "
          f"flax: {vgg_ref:.1f}", file=sys.stderr)
    if peak:
        _check_plausible(_mfu(VGG16_FWD_FLOPS, max(vgg, vgg_ref),
                              False, peak), "vgg16")
    return {
        "metric": ("Keras-imported VGG16 inference (batch "
                   f"{VGG_BATCH}, 224x224, f32)"),
        "value": round(vgg, 1), "unit": "images/sec/chip",
        "baseline": round(vgg_ref, 1),
        "vs_baseline": round(vgg / vgg_ref, 3),
        "mfu": round(_mfu(VGG16_FWD_FLOPS, vgg, False, peak), 4)
        if peak else None,
        "note": ("gap analysis (round 4): ours and the flax reference "
                 "compile to identical XLA work — cost_analysis flops "
                 "9.591e11 and bytes-accessed 4.654e9 match to 4 "
                 "digits — so any measured ratio away from 1.0 on "
                 "this leg is tunnel timing noise (observed spread "
                 "0.944-1.059 across 5 runs), not a framework cost")}


def _ensure_png_tree(root, n_classes=10, per_class=52, hw=224):
    """Directory-per-label PNG tree for the ETL leg (cached across
    runs; ~78MB of noise PNGs — noise compresses worst, so decode
    cost is an upper bound)."""
    import json
    stamp = os.path.join(root, "stamp.json")
    want = {"n_classes": n_classes, "per_class": per_class, "hw": hw}
    if os.path.exists(stamp):
        with open(stamp) as f:
            if json.load(f) == want:
                return root
    if os.path.isdir(root):
        # stale or half-generated tree (config mismatch, or a run
        # killed before the stamp was written): clear it, or leftover
        # files silently inflate the dataset the numbers claim
        import shutil
        shutil.rmtree(root)
    from PIL import Image
    rng = np.random.default_rng(0)
    for c in range(n_classes):
        d = os.path.join(root, f"class{c}")
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            a = rng.integers(0, 256, (hw, hw, 3), dtype=np.uint8)
            Image.fromarray(a).save(os.path.join(d, f"im{i}.png"))
    with open(stamp, "w") as f:
        json.dump(want, f)
    return root


def _leg_resnet_native_etl(peak):
    """Train ResNet50 FROM A PNG TREE through the native libpng worker
    pool (reference RecordReaderDataSetIterator.java:52 +
    AsyncDataSetIterator.java:30 — 'the device never waits'). Round-5
    shape (round-4 verdict next #2): measure (a) decode-thread
    scaling, (b) the decode-ahead OVERLAP with a tunnel-free
    simulated compute consumer — proving the bounded queue hides
    decode latency behind any compute >= decode, (c) the per-batch
    host->device upload in isolation (the tunnel tax), then (d) the
    honest end-to-end number with the exposure attributed."""
    from deeplearning4j_tpu.data.native_loader import (
        NativeImageDataSetIterator, native_image_available)
    if not native_image_available():
        raise ImportError("native image loader unavailable (g++/libpng)")
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.conf import updaters
    from deeplearning4j_tpu.zoo import ResNet50

    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".bench_cache")
    tree = _ensure_png_tree(os.path.join(cache_dir, "png_tree_224"))
    batch = 128
    host_cores = os.cpu_count() or 1

    def make_it(nt=4):
        # ONE loader config for every measured section — decode,
        # overlap, warmup and e2e must describe the same pipeline
        return NativeImageDataSetIterator(tree, batch, 224, 224, 3,
                                          n_threads=nt,
                                          queue_capacity=4)

    def decode_pass(nt, consume_sleep_s=0.0):
        """STEADY-STATE decode ms/full-batch at n_threads=nt (first
        batch dropped: it pays pool spin-up + directory scan), min of
        2 passes. With consume_sleep_s the consumer simulates a
        device step that long (sleep holds no GIL and no core, so the
        worker pool decodes ahead into the queue — measuring what the
        queue can HIDE, with no tunnel in the loop)."""
        best = float("inf")
        for _ in range(2):
            it = make_it(nt)
            gaps = []
            last = time.perf_counter()
            for ds in it:
                if ds.num_examples() == batch:
                    now = time.perf_counter()
                    gaps.append(now - last)
                    if consume_sleep_s:
                        time.sleep(consume_sleep_s)
                    last = time.perf_counter()
            if len(gaps) > 1:
                gaps = gaps[1:]
            dt = sum(gaps) / max(1, len(gaps)) * 1e3
            best = min(best, dt)
        return best

    # (a) decode scaling over worker counts (on a 1-core host this is
    # flat by construction — that IS the measured evidence that the
    # host, not the loader, is the ceiling here)
    scaling = {nt: round(decode_pass(nt), 1) for nt in (1, 2, 4)}
    decode_ms = scaling[4]

    # (b) overlap proof: consumer sleeps decode_ms per batch (a
    # stand-in for any device step >= decode). With consume_sleep_s
    # set, decode_pass times only the post-step wait + batch
    # materialization — the EXPOSED ETL under overlap directly; a
    # small constant (the consumer-side memcpy of the 60MB batch)
    # proves the queue hides the actual DECODE entirely.
    exposed_sim = decode_pass(4, consume_sleep_s=decode_ms / 1e3)
    # slack case (step = 2x decode): on a host with ANY headroom the
    # exposure floor is just the batch hand-off, proving the queue
    # hides the decode itself
    exposed_slack = decode_pass(4, consume_sleep_s=2 * decode_ms / 1e3)

    # (c) + (d): the real device path
    net = ResNet50(n_classes=10, input_shape=(224, 224, 3),
                   updater=updaters.nesterovs(0.1, 0.9)).init()
    step = net._make_train_step()
    key = jax.random.PRNGKey(0)
    first = next(iter(make_it()))
    bt = net._batch_tuple(net._as_multi(first))
    p, s, o, loss = step(net.params, net.state, net.opt_state, bt, key,
                         np.int32(0))
    float(jnp.sum(loss))

    # (c) upload tax in isolation: host->device transfer of one
    # batch's features (fresh numpy each time so nothing caches)
    up = float("inf")
    feats = np.asarray(first.features[0] if isinstance(
        first.features, (list, tuple)) else first.features)
    for i in range(3):
        fresh = feats + np.float32(i + 1)       # defeat content dedupe
        t0 = time.perf_counter()
        a = jax.device_put(fresh)
        # minimal data-dependent fetch as the sync: a full jnp.sum
        # would bill a 77MB on-device reduction to the 'upload tax'
        float(a[0, 0, 0, 0])
        up = min(up, time.perf_counter() - t0)
    upload_ms = up * 1e3

    # pure step: cached batch burst
    t0 = time.perf_counter()
    for _ in range(10):
        p, s, o, loss = step(p, s, o, bt, key, np.int32(0))
    float(jnp.sum(loss))
    step_ms = (time.perf_counter() - t0) / 10 * 1e3

    # (d) end-to-end epochs from PNGs
    n_img = 0
    it = make_it()
    t0 = time.perf_counter()
    for _ in range(2):
        for ds in it:
            if ds.num_examples() != batch:
                continue
            bt2 = net._batch_tuple(net._as_multi(ds))
            p, s, o, loss = step(p, s, o, bt2, key, np.int32(0))
            n_img += batch
    float(jnp.sum(loss))
    e2e = time.perf_counter() - t0
    e2e_ms = e2e / (n_img / batch) * 1e3
    rate = n_img / e2e
    exposed = max(0.0, e2e_ms - step_ms)
    print(f"native-etl: decode scaling {scaling} ms/batch, "
          f"overlap-exposed {exposed_sim:.1f} ms (at 2x step: "
          f"{exposed_slack:.1f}), upload {upload_ms:.1f} ms, step "
          f"{step_ms:.1f} ms, e2e {e2e_ms:.1f} ms/batch "
          f"({rate:.1f} img/s), cores {host_cores}", file=sys.stderr)
    return {
        "metric": ("ResNet50 train-from-PNG-tree via native ETL "
                   "(batch 128, 224x224, f32)"),
        "value": round(rate, 1), "unit": "images/sec/chip",
        "baseline": None, "vs_baseline": None,
        "decode_ms_per_batch_by_threads": scaling,
        "overlap_exposed_ms_per_batch": round(exposed_sim, 1),
        "overlap_exposed_ms_at_2x_step": round(exposed_slack, 1),
        "upload_ms_per_batch": round(upload_ms, 1),
        "step_ms_per_batch": round(step_ms, 1),
        "e2e_ms_per_batch": round(e2e_ms, 1),
        "exposed_etl_ms_per_batch": round(exposed, 1),
        "host_cores": host_cores,
        "note": ("overlap_exposed = measured post-step wait + batch "
                 "hand-off under a GIL-free simulated step (no tunnel "
                 "in the loop): at step=decode a 1-core host is "
                 "saturated (decode competes with the consumer), at "
                 "step=2x decode the exposure drops to the hand-off "
                 "floor — the bounded queue hides the DECODE itself "
                 "(AsyncDataSetIterator.java:30 'device never "
                 "waits'). Round 5 removed the consumer-side second "
                 "copy (fresh per-batch arrays, native memcpy only). "
                 "The e2e gap beyond step_ms decomposes into "
                 "upload_ms (the ~77MB/batch host->device transfer — "
                 "through the axon tunnel this is network, on a "
                 "TPU-VM host a PCIe copy) plus unhidden decode on "
                 "this host; the 1->2->4 thread scaling table "
                 "documents whether cores or the loader are the "
                 "ceiling (flat scaling on a 1-core host = "
                 "host-bound by construction)")}


LM_B, LM_T, LM_D, LM_L, LM_H, LM_V = 8, 1024, 1024, 8, 16, 2048
LM_STEPS = 20
# causal-corrected model FLOPs per token, forward: per layer 24*D^2
# (qkv/o/mlp matmuls) + 2*T*D (causal attention: half the T^2 tiles),
# plus the 2*D*V head; embedding gather ~0. Train = 3x forward.
LM_FWD_FLOPS_PER_TOK = LM_L * (24 * LM_D * LM_D + 2 * LM_T * LM_D) \
    + 2 * LM_D * LM_V


def bench_ours_transformer_lm(prep=False):
    """Config-built decoder-only LM through the framework surface:
    EmbeddingSequence + 8 pre-LN TransformerEncoderLayers (causal
    flash kernels) + RnnOutputLayer, bf16 compute policy — the
    high-MFU showcase (round-3 verdict weak #2)."""
    import jax

    from deeplearning4j_tpu import (MultiLayerNetwork,
                                    NeuralNetConfiguration, dtypes)
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import updaters
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (
        EmbeddingSequenceLayer, RnnOutputLayer, TransformerEncoderLayer)

    b = (NeuralNetConfiguration.builder().set_seed(0)
         .updater(updaters.adam(1e-3)).list()
         .layer(EmbeddingSequenceLayer(n_in=LM_V, n_out=LM_D)))
    for _ in range(LM_L):
        b = b.layer(TransformerEncoderLayer(n_heads=LM_H, causal=True))
    conf = (b.layer(RnnOutputLayer(n_out=LM_V, loss="mcxent"))
            .set_input_type(InputType.recurrent(LM_V, LM_T)).build())
    with dtypes.policy_scope(dtypes.tpu_bf16()):
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(0)
        ids = rng.integers(0, LM_V, (LM_B, LM_T)).astype("float32")
        y = np.eye(LM_V, dtype="float32")[
            rng.integers(0, LM_V, (LM_B, LM_T))]
        batch_t = net._batch_tuple(DataSet(ids, y))
        step = net._make_train_step()
        key = jax.random.PRNGKey(0)
        it = np.int32(0)

        def one(params, state, opt, loss):
            return step(params, state, opt, batch_t, key, it)

        m = _make_measure(one, (net.params, net.state, net.opt_state,
                                None), LM_STEPS, WARMUP,
                          lambda a: a[3])
    if prep:
        return m
    return LM_STEPS * LM_B * LM_T / m()


def bench_flax_transformer_lm(prep=False):
    """The same pre-LN decoder in flax linen (nn.SelfAttention with a
    causal mask — XLA-fused exact attention), bf16 module dtype."""
    import jax
    import jax.numpy as jnp
    import optax
    from flax import linen as nn

    dt = jnp.bfloat16

    class Block(nn.Module):
        @nn.compact
        def __call__(self, x):
            h = nn.LayerNorm(dtype=dt)(x)
            h = nn.SelfAttention(
                num_heads=LM_H, dtype=dt, deterministic=True)(
                h, mask=nn.make_causal_mask(
                    jnp.ones((x.shape[0], x.shape[1]))))
            x = x + h
            h = nn.LayerNorm(dtype=dt)(x)
            h = nn.Dense(4 * LM_D, dtype=dt)(h)
            h = nn.gelu(h)
            h = nn.Dense(LM_D, dtype=dt)(h)
            return x + h

    class LM(nn.Module):
        @nn.compact
        def __call__(self, ids):
            x = nn.Embed(LM_V, LM_D, dtype=dt)(ids)
            for _ in range(LM_L):
                x = Block()(x)
            return nn.Dense(LM_V, dtype=dt)(x)

    model = LM()
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, LM_V, (LM_B, LM_T)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, LM_V, (LM_B, LM_T)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)
    tx = optax.adam(1e-3)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, loss_prev):
        def loss_fn(p):
            logits = model.apply(p, ids).astype(jnp.float32)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()
        loss, g = jax.value_and_grad(loss_fn)(params)
        up, opt2 = tx.update(g, opt, params)
        return optax.apply_updates(params, up), opt2, loss

    m = _make_measure(step, (params, opt, None),
                      LM_STEPS, WARMUP, lambda a: a[2])
    if prep:
        return m
    return LM_STEPS * LM_B * LM_T / m()


def _leg_transformer_lm(peak):
    m_ours = bench_ours_transformer_lm(prep=True)
    m_ref = bench_flax_transformer_lm(prep=True)
    dt_o, dt_r = _interleave(m_ours, m_ref, repeats=3)
    toks = LM_STEPS * LM_B * LM_T
    ours = toks / dt_o
    ref = toks / dt_r
    print(f"transformer-lm ours(flash,bf16): {ours:.0f} tok/s, flax "
          f"(exact attn,bf16): {ref:.0f}", file=sys.stderr)
    if peak:
        _check_plausible(_mfu(LM_FWD_FLOPS_PER_TOK, max(ours, ref),
                              True, peak), "transformer-lm")
    return {
        "metric": (f"Transformer-LM train throughput (B={LM_B}, "
                   f"T={LM_T}, d={LM_D}, L={LM_L}, heads={LM_H}, "
                   f"vocab {LM_V}, bf16)"),
        "value": round(ours, 0), "unit": "tokens/sec/chip",
        "baseline": round(ref, 0),
        "vs_baseline": round(ours / ref, 3),
        "mfu": round(_mfu(LM_FWD_FLOPS_PER_TOK, ours, True, peak), 4)
        if peak else None,
        "note": ("ours: config-built MLN (EmbeddingSequence + 8 "
                 "causal TransformerEncoderLayers + RnnOutputLayer), "
                 "Pallas flash kernels, bf16 policy; baseline: same "
                 "arch in flax linen, nn.SelfAttention causal-masked "
                 "exact attention, bf16; causal-corrected model "
                 "FLOPs (attention counted at T^2/2)")}


def _leg_flash_attention(peak):
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.ops.attention import flash_attention
    B, T, H, D = 4, 4096, 8, 64
    rngk = jax.random.PRNGKey(0)
    q = jax.random.normal(rngk, (B, T, H, D), jnp.float32)

    def naive(q, k, v):
        qh = jnp.swapaxes(q, 1, 2)
        kh = jnp.swapaxes(k, 1, 2)
        vh = jnp.swapaxes(v, 1, 2)
        s = qh @ jnp.swapaxes(kh, -1, -2) / np.sqrt(D)
        return jnp.swapaxes(jax.nn.softmax(s) @ vh, 1, 2)

    def mk(fn):
        # CHAIN the gradient through the next input — identical
        # repeated calls get deduped by the tunnel'd runtime and
        # time as ~0. grad(q) has q's shape, so it feeds back.
        g = jax.jit(jax.grad(lambda x: jnp.sum(fn(x, x, x) ** 2)))
        float(jnp.sum(g(q)))                # compile + warm (fetch-sync)

        def measure():
            # large burst: the tunnel's ~130 ms fixed sync cost is a
            # few percent of 100 chained steps; min-of-N by the
            # caller; host FETCH as the end-of-burst sync (block is a
            # no-op for non-donated arrays through the tunnel)
            a = q
            t0 = time.perf_counter()
            for _ in range(100):
                a = g(a)
            float(jnp.sum(a))
            return (time.perf_counter() - t0) / 100
        return measure

    m_flash = mk(lambda a, b, c: flash_attention(a, b, c))
    m_naive = mk(naive)
    dt_f, dt_n = _interleave(m_flash, m_naive, repeats=3)
    toks = B * T
    attn_flops = 14 * T * T * D * B * H

    # the REAL bar (round-3 verdict weak #3): JAX's bundled production
    # TPU flash kernel, given the same 1024^2 tiles ours auto-selects
    # (its defaults — 128-col k blocks — are 5x slower at this config,
    # so tuning it is the fair comparison). Seam contract = fastest
    # algorithm (reference CudnnConvolutionHelper.java:156-192).
    try:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            BlockSizes)
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as prod_flash)
        bs = BlockSizes(
            block_q=1024, block_k_major=1024, block_k=1024, block_b=1,
            block_q_major_dkv=1024, block_k_major_dkv=1024,
            block_k_dkv=1024, block_q_dkv=1024,
            block_k_major_dq=1024, block_k_dq=1024, block_q_dq=1024)

        def prod(a, b, c):
            ah, bh, ch = (jnp.swapaxes(x, 1, 2) for x in (a, b, c))
            o = prod_flash(ah, bh, ch, sm_scale=1.0 / np.sqrt(D),
                           block_sizes=bs)
            return jnp.swapaxes(o, 1, 2)

        m_prod = mk(prod)
        # interleave against OURS in its own window (host drift
        # between windows lands asymmetrically, so each ratio comes
        # from alternating bursts within ONE window): vs_baseline
        # stays (dt_f, dt_n) from window 1, vs_production_kernel is
        # (dt_f2, dt_p) from window 2 — dt_f2 is NOT folded into the
        # headline value
        dt_f2, dt_p = _interleave(m_flash, m_prod, repeats=3)
        prod_ratio = dt_p / dt_f2
        prod_note = (f"vs jax.experimental.pallas.ops.tpu."
                     f"flash_attention (tuned to the same 1024^2 "
                     f"tiles): ours {prod_ratio:.3f}x its speed")
        print(f"flash vs production kernel: ours {toks/dt_f2:.0f} "
              f"tok/s, prod {toks/dt_p:.0f} tok/s "
              f"(ours/prod {prod_ratio:.3f}x)", file=sys.stderr)
    except Exception as e:           # older jax layouts: informational
        dt_f2 = dt_p = None
        prod_ratio = None
        prod_note = f"production-kernel comparison unavailable: {e}"
    if peak and dt_p is not None:
        # OUTSIDE the except: a degraded-tunnel window must abort the
        # leg (orchestrator retries), not demote to a note
        _check_plausible(attn_flops / dt_p / peak,
                         "flash production-kernel baseline")
        _check_plausible(attn_flops / dt_f2 / peak,
                         "flash (production-comparison window)")
    print(f"flash attention T=4096 fwd+bwd: {toks/dt_f:.0f} "
          f"tok/s vs naive {toks/dt_n:.0f}", file=sys.stderr)
    if peak:
        _check_plausible(attn_flops / min(dt_f, dt_n) / peak,
                         "flash attention")
    return {
        "metric": ("flash attention fwd+bwd (B=4, T=4096, "
                   "H=8, D=64, f32)"),
        "value": round(toks / dt_f, 0), "unit": "tokens/sec",
        "baseline": round(toks / dt_n, 0),
        "vs_baseline": round(dt_n / dt_f, 3),
        "vs_production_kernel": (round(prod_ratio, 3)
                                 if prod_ratio is not None else None),
        "mfu": round(attn_flops / dt_f / peak, 4) if peak else None,
        "note": ("baseline = naive attention (materializes TxT); "
                 "both at XLA default matmul precision; Pallas "
                 "fwd+bwd kernels, auto 1024^2 tiles; " + prod_note)}


SERVE_CONC = 32           # closed-loop clients
SERVE_REQUESTS = 1536     # total requests through the scheduler
SERVE_SEQ_REQUESTS = 256  # sequential-baseline sample


def _leg_serving_throughput(peak):
    """The serving subsystem's in-process number (no HTTP in the
    loop): requests/sec and tail latency at fixed concurrency through
    ``serving.BatchScheduler`` — SERVE_CONC closed-loop clients each
    firing 1-row predicts back-to-back — vs the same model called
    sequentially one request at a time (what a front end without
    dynamic batching would do). The ratio is the value of coalescing
    concurrent requests into few large, shape-stable device calls."""
    import threading

    from deeplearning4j_tpu import (MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf import updaters
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                   OutputLayer)
    from deeplearning4j_tpu.serving.metrics import ServingMetrics
    from deeplearning4j_tpu.serving.scheduler import BatchScheduler

    feat, hidden, classes, max_bs = 32, 128, 16, 64
    conf = (NeuralNetConfiguration.builder().set_seed(0)
            .updater(updaters.adam(1e-3)).list()
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(OutputLayer(n_out=classes, loss="mcxent"))
            .set_input_type(InputType.feed_forward(feat)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    xs = rng.normal(0, 1, (SERVE_CONC, 1, feat)).astype("float32")

    # warm every power-of-two batch shape the scheduler can emit, so
    # the measured window holds zero compiles
    s = 1
    while s <= max_bs:
        np.asarray(net.output(np.zeros((s, feat), np.float32)))
        s *= 2

    # sequential baseline: one request at a time, no coalescing
    t0 = time.perf_counter()
    for i in range(SERVE_SEQ_REQUESTS):
        np.asarray(net.output(xs[i % SERVE_CONC]))
    seq_rps = SERVE_SEQ_REQUESTS / (time.perf_counter() - t0)

    metrics = ServingMetrics()
    sched = BatchScheduler(net, max_batch_size=max_bs,
                           queue_limit=4 * SERVE_CONC, wait_ms=1.0,
                           metrics=metrics)
    per_client = SERVE_REQUESTS // SERVE_CONC
    errs = []

    def client(c):
        try:
            for _ in range(per_client):
                sched.predict(xs[c])
        except BaseException as e:      # surfaced below, fails the leg
            errs.append(e)

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(SERVE_CONC)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    sched.shutdown()
    if errs:
        raise errs[0]
    served = per_client * SERVE_CONC
    rps = served / dt
    snap = metrics.snapshot()
    ep = snap["endpoints"]["predict"]
    occ = snap["batching"]["predict"]
    print(f"serving: {rps:.0f} req/s at {SERVE_CONC} clients "
          f"(p50 {ep['latency']['p50_ms']:.1f} ms, p99 "
          f"{ep['latency']['p99_ms']:.1f} ms, avg batch "
          f"{occ['avg_batch_size']:.1f}); sequential {seq_rps:.0f} "
          "req/s", file=sys.stderr)
    return {
        "metric": (f"serving scheduler throughput (closed loop, "
                   f"{SERVE_CONC} clients, 1-row requests, MLP "
                   f"{feat}-{hidden}-{hidden}-{classes})"),
        "value": round(rps, 1), "unit": "requests/sec",
        "baseline": round(seq_rps, 1),
        "vs_baseline": round(rps / seq_rps, 3),
        "p50_ms": ep["latency"]["p50_ms"],
        "p99_ms": ep["latency"]["p99_ms"],
        "avg_batch_size": occ["avg_batch_size"],
        "max_batch_size_seen": occ["max_batch_size_seen"],
        "mfu": None,
        "note": ("value: serving.BatchScheduler (dynamic batching, "
                 "pow2 shape buckets, 1 ms window) under "
                 f"{SERVE_CONC} concurrent closed-loop clients; "
                 "baseline: the same model called one request at a "
                 "time — the no-batching front end. All compiled "
                 "shapes pre-warmed; in-process, no HTTP")}


TRACE_SAMPLE_RATES = (0.0, 0.01, 1.0)
TRACE_OVERHEAD_BAR = 0.02      # ≤2% throughput cost at 1% sampling


def _leg_tracing_overhead(peak):
    """What request-scoped tracing costs the serving hot path: the
    serving_throughput harness re-run at head-sampling 0% / 1% /
    100%. Every request carries a RequestContext (the phase ledger
    feeds the attribution histograms unconditionally); sampling only
    gates span EMISSION — so the 1%-vs-0% delta is the number the
    default config actually pays. Bar: ≤2% at 1% sampling."""
    import threading

    from deeplearning4j_tpu import (MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf import updaters
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                   OutputLayer)
    from deeplearning4j_tpu.observability.tracing import (
        RequestContext, Sampler, trace)
    from deeplearning4j_tpu.serving.metrics import ServingMetrics
    from deeplearning4j_tpu.serving.scheduler import BatchScheduler

    feat, hidden, classes, max_bs = 32, 128, 16, 64
    conf = (NeuralNetConfiguration.builder().set_seed(0)
            .updater(updaters.adam(1e-3)).list()
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(OutputLayer(n_out=classes, loss="mcxent"))
            .set_input_type(InputType.feed_forward(feat)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    xs = rng.normal(0, 1, (SERVE_CONC, 1, feat)).astype("float32")
    s = 1
    while s <= max_bs:
        np.asarray(net.output(np.zeros((s, feat), np.float32)))
        s *= 2

    def run_at(rate):
        sampler = Sampler(rate=rate)
        metrics = ServingMetrics()
        sched = BatchScheduler(net, max_batch_size=max_bs,
                               queue_limit=4 * SERVE_CONC,
                               wait_ms=1.0, metrics=metrics)
        per_client = SERVE_REQUESTS // SERVE_CONC
        errs = []

        def client(c):
            try:
                for _ in range(per_client):
                    ctx = RequestContext.new(
                        "/v1/predict", sampler)
                    sched.predict(xs[c], ctx=ctx)
            except BaseException as e:
                errs.append(e)

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(SERVE_CONC)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        sched.shutdown()
        if errs:
            raise errs[0]
        trace.clear()     # don't let the 100% run's buffer linger
        return per_client * SERVE_CONC / dt

    # PAIRED back-to-back runs, median of ratios: single-run
    # scheduler throughput swings ±50% on a noisy host and the drift
    # is not monotone, so best-of / averaged absolute numbers charge
    # machine weather to whichever rate ran at the wrong time. A
    # ratio within one adjacent pair cancels the drift; the median
    # over pairs (with pair order alternating) is robust to the
    # outlier rounds. This is the same drift problem the interleaved
    # bench_ours/bench_ref measurement solves, at percent scale.
    import statistics

    def paired_ratio(rate, pairs=6):
        ratios = []
        for i in range(pairs):
            if i % 2 == 0:
                base, test = run_at(0.0), run_at(rate)
            else:
                test, base = run_at(rate), run_at(0.0)
            ratios.append(test / base)
        return statistics.median(ratios)

    rel_1pct = paired_ratio(0.01)
    rel_full = paired_ratio(1.0)
    rps_base = run_at(0.0)
    overhead_1pct = max(0.0, 1.0 - rel_1pct)
    overhead_full = max(0.0, 1.0 - rel_full)
    print(f"tracing overhead: ~{rps_base:.0f} req/s; 1% sampling "
          f"{rel_1pct:.3f}x of unsampled "
          f"({overhead_1pct * 100:.1f}% cost), 100% sampling "
          f"{rel_full:.3f}x ({overhead_full * 100:.1f}% cost)",
          file=sys.stderr)
    return {
        "metric": (f"request-tracing overhead (serving scheduler, "
                   f"{SERVE_CONC} closed-loop clients, 1-row "
                   "requests)"),
        "value": round(rel_1pct, 3),
        "unit": "throughput ratio (1% sampling / unsampled)",
        "baseline": 1.0,
        "vs_baseline": round(rel_1pct, 3),
        "rps_unsampled": round(rps_base, 1),
        "ratio_sampled_100pct": round(rel_full, 3),
        "overhead_at_1pct": round(overhead_1pct, 4),
        "overhead_at_100pct": round(overhead_full, 4),
        "bar_overhead_at_1pct": TRACE_OVERHEAD_BAR,
        "passed_bar": bool(overhead_1pct <= TRACE_OVERHEAD_BAR),
        "mfu": None,
        "note": ("serving_throughput harness with every request "
                 "carrying a RequestContext; sampling gates span "
                 "emission only (phase ledger + attribution "
                 "histograms record at EVERY rate). Median of 6 "
                 "paired back-to-back ratios, pair order "
                 "alternating — drift-robust on noisy hosts; "
                 "bar: ≤2% cost at 1% sampling")}


ROUTER_CONC = 16          # closed-loop clients against the router
ROUTER_REQUESTS = 600     # per fleet size


def _leg_router_fleet(peak):
    """The fleet's robustness headline: sustained QPS and p99
    through the health-aware router at N=1 vs N=4 SUBPROCESS
    replicas (real processes — no shared GIL, and the SIGKILL is a
    literal signal 9), then N=4 again with one replica killed
    mid-run by a seeded ``serving.replica`` chaos fault. The kill
    run must drop ZERO requests (failover absorbs the death) — the
    number the soak acceptance turns into a measured claim."""
    import subprocess
    import tempfile
    import urllib.request

    from deeplearning4j_tpu import (MultiLayerNetwork,
                                    NeuralNetConfiguration, chaos)
    from deeplearning4j_tpu.nn.conf import updaters
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                   OutputLayer)
    from deeplearning4j_tpu.serving.fleet import ReplicaFleet
    from deeplearning4j_tpu.serving.router import Router
    from deeplearning4j_tpu.util.model_serializer import write_model

    feat, hidden, classes, max_bs = 32, 128, 16, 32
    conf = (NeuralNetConfiguration.builder().set_seed(0)
            .updater(updaters.adam(1e-3)).list()
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(OutputLayer(n_out=classes, loss="mcxent"))
            .set_input_type(InputType.feed_forward(feat)).build())
    tmp = tempfile.mkdtemp(prefix="bench_fleet_")
    model_zip = os.path.join(tmp, "mlp.zip")
    write_model(MultiLayerNetwork(conf).init(), model_zip)

    def loadgen(router_port, total, retries=3):
        # loadgen runs OUT of process: client threads inside this
        # process would share the router's GIL and measure their
        # own contention, not the fleet's throughput
        proc = subprocess.run(
            [sys.executable, "-m", "tools.loadgen",
             "--url", f"http://127.0.0.1:{router_port}",
             "--features", str(feat),
             "--concurrency", str(ROUTER_CONC),
             "--total", str(total),
             "--timeout", "30", "--retries", str(retries)],
            capture_output=True, text=True, timeout=300,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if not proc.stdout.strip():
            # a crashed loadgen child must surface its own
            # diagnostic, not an opaque JSONDecodeError on ''.
            # NOTE: exit 1 with a report on stdout just means
            # failed>0 — that report is the measurement (the SIGKILL
            # leg asserts on its failed/errors fields), never raise
            raise RuntimeError(
                f"loadgen exited {proc.returncode} with no report; "
                f"stderr: {proc.stderr[-800:]}")
        return json.loads(proc.stdout)

    def run(n, base_port, kill_at=None):
        fleet = ReplicaFleet(
            model_specs=[f"default={model_zip}"], n=n,
            base_port=base_port).start()
        router = Router(fleet, probe_interval_s=0.25,
                        hedge_after_s=None, sample_rate=0.0).start()
        try:
            # readiness gate: subprocess replicas import jax and
            # restore the model before they listen — wait until the
            # router's prober sees every replica up
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{router.port}"
                            "/healthz", timeout=5.0) as r:
                        if json.load(r).get("eligible") == n:
                            break
                except OSError:
                    pass
                time.sleep(0.25)
            else:
                raise RuntimeError(
                    f"fleet of {n} never became ready")
            # warmup OUTSIDE the measured window: first requests
            # compile each pow2 batch shape on every replica
            loadgen(router.port, 8 * ROUTER_CONC * n)
            if kill_at is not None:
                chaos.install({"faults": [
                    {"site": "serving.replica", "kind": "kill",
                     "at": [kill_at], "args": {"replica": 0}}]},
                    seed=1234)
            rep = loadgen(router.port, ROUTER_REQUESTS)
        finally:
            chaos.uninstall()
            router.stop()
            fleet.stop(drain=False, timeout=5.0)
        return rep

    r1 = run(1, 18310)
    r4 = run(4, 18320)
    rk = run(4, 18330, kill_at=ROUTER_REQUESTS // 3)
    if rk["failed"] or r4["failed"] or r1["failed"]:
        raise RuntimeError(
            f"router_fleet dropped requests: n1={r1['failed']} "
            f"n4={r4['failed']} kill={rk['failed']} "
            f"({rk['errors']})")
    print(f"router_fleet: N=1 {r1['achieved_qps']:.0f} q/s p99 "
          f"{r1['latency_ms']['p99']:.1f} ms; N=4 "
          f"{r4['achieved_qps']:.0f} q/s p99 "
          f"{r4['latency_ms']['p99']:.1f} ms; N=4+SIGKILL "
          f"{rk['achieved_qps']:.0f} q/s p99 "
          f"{rk['latency_ms']['p99']:.1f} ms, 0 dropped",
          file=sys.stderr)
    return {
        "metric": (f"serving fleet sustained QPS through the "
                   f"router (closed loop, {ROUTER_CONC} clients, "
                   f"1-row MLP predicts, N=4 subprocess replicas)"),
        "value": r4["achieved_qps"], "unit": "requests/sec",
        "baseline": r1["achieved_qps"],
        "vs_baseline": round(r4["achieved_qps"]
                             / max(r1["achieved_qps"], 1e-9), 3),
        "p99_n1_ms": r1["latency_ms"]["p99"],
        "p99_n4_ms": r4["latency_ms"]["p99"],
        "p99_n4_sigkill_ms": rk["latency_ms"]["p99"],
        "qps_n4_sigkill": rk["achieved_qps"],
        "sigkill_dropped": rk["failed"],
        "sigkill_retries": rk["retries"],
        "host_cpus": os.cpu_count(),
        "mfu": None,
        "note": ("value: N=4 subprocess-replica fleet behind "
                 "serving/router.py (health probes, least-loaded "
                 "balancing, failover; hedging off); baseline: the "
                 "same router over N=1. The SIGKILL row reruns N=4 "
                 "with a seeded serving.replica chaos kill (a real "
                 "signal 9 to the child) at request ordinal "
                 f"{ROUTER_REQUESTS // 3}: zero dropped requests — "
                 "failover absorbs the death, the tail pays for "
                 "it. Replicas are separate processes on loopback "
                 "HTTP, one physical host — QPS measures the "
                 "router+fleet stack, not multi-host scale-out")}


OBS_OVERHEAD_BAR = 0.02   # ≤2% QPS cost with 1 s collector scrapes
# per measured run: ~2.4k requests ≈ 7 s at this host's QPS, so each
# window samples several whole scrape cycles — 600-request windows
# are shorter than the scrape interval and measure boundary luck
OBS_REQUESTS = 2400


def _leg_observability_overhead(peak):
    """What the fleet observability plane costs the serving path: the
    router_fleet harness (N=2 subprocess replicas, out-of-process
    loadgen) re-run with a FleetCollector scraping every member's
    /metrics + /debug/trace-export at a 1 s interval, vs collector
    off. The collector is pull-based and out of the request path, so
    the cost is bounded by the /metrics render under load.
    Bar: ≤2% QPS cost."""
    import statistics
    import subprocess
    import tempfile
    import urllib.request

    from deeplearning4j_tpu import (MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf import updaters
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                   OutputLayer)
    from deeplearning4j_tpu.observability.fleetobs import (
        FleetCollector)
    from deeplearning4j_tpu.serving.fleet import ReplicaFleet
    from deeplearning4j_tpu.serving.router import Router
    from deeplearning4j_tpu.util.model_serializer import write_model

    feat, hidden, classes = 32, 128, 16
    conf = (NeuralNetConfiguration.builder().set_seed(0)
            .updater(updaters.adam(1e-3)).list()
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(OutputLayer(n_out=classes, loss="mcxent"))
            .set_input_type(InputType.feed_forward(feat)).build())
    tmp = tempfile.mkdtemp(prefix="bench_obs_")
    model_zip = os.path.join(tmp, "mlp.zip")
    write_model(MultiLayerNetwork(conf).init(), model_zip)

    def loadgen(router_port, total):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.loadgen",
             "--url", f"http://127.0.0.1:{router_port}",
             "--features", str(feat),
             "--concurrency", str(ROUTER_CONC),
             "--total", str(total),
             "--timeout", "30", "--retries", "3"],
            capture_output=True, text=True, timeout=300,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if not proc.stdout.strip():
            raise RuntimeError(
                f"loadgen exited {proc.returncode} with no report; "
                f"stderr: {proc.stderr[-800:]}")
        return json.loads(proc.stdout)

    n = 2
    fleet = ReplicaFleet(model_specs=[f"default={model_zip}"], n=n,
                         base_port=18350).start()
    router = Router(fleet, probe_interval_s=0.25,
                    hedge_after_s=None, sample_rate=0.01).start()
    try:
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{router.port}/healthz",
                        timeout=5.0) as r:
                    if json.load(r).get("eligible") == n:
                        break
            except OSError:
                pass
            time.sleep(0.25)
        else:
            raise RuntimeError(f"fleet of {n} never became ready")
        loadgen(router.port, 8 * ROUTER_CONC * n)    # warmup/compile

        def run_with_collector():
            col = FleetCollector(fleet=fleet, router=router,
                                 interval_s=1.0, port=0).start()
            router.attach_fleet_health(col.fleet_health)
            try:
                return loadgen(router.port, OBS_REQUESTS)
            finally:
                router.attach_fleet_health(None)
                col.stop()

        def run_without():
            return loadgen(router.port, OBS_REQUESTS)

        # PAIRED back-to-back ratios, median over alternating-order
        # pairs — the same drift-robust shape as tracing_overhead
        ratios, qps_off, qps_on, dropped = [], [], [], 0
        for i in range(4):
            if i % 2 == 0:
                off, on = run_without(), run_with_collector()
            else:
                on, off = run_with_collector(), run_without()
            for rep in (off, on):
                dropped += rep["failed"]
            qps_off.append(off["achieved_qps"])
            qps_on.append(on["achieved_qps"])
            ratios.append(on["achieved_qps"]
                          / max(off["achieved_qps"], 1e-9))
        rel = statistics.median(ratios)
    finally:
        router.stop()
        fleet.stop(drain=False, timeout=5.0)
    if dropped:
        raise RuntimeError(
            f"observability_overhead dropped {dropped} requests")
    overhead = max(0.0, 1.0 - rel)
    print(f"observability overhead: scraped "
          f"{statistics.median(qps_on):.0f} q/s vs unscraped "
          f"{statistics.median(qps_off):.0f} q/s → {rel:.3f}x "
          f"({overhead * 100:.1f}% cost)", file=sys.stderr)
    return {
        "metric": (f"fleet-collector scrape overhead (router over "
                   f"N={n} subprocess replicas, {ROUTER_CONC} "
                   "closed-loop clients, 1 s scrape interval)"),
        "value": round(rel, 3),
        "unit": "throughput ratio (collector on / off)",
        "baseline": 1.0,
        "vs_baseline": round(rel, 3),
        "qps_collector_on": round(statistics.median(qps_on), 1),
        "qps_collector_off": round(statistics.median(qps_off), 1),
        "overhead": round(overhead, 4),
        "bar_overhead": OBS_OVERHEAD_BAR,
        "passed_bar": bool(overhead <= OBS_OVERHEAD_BAR),
        "host_cpus": os.cpu_count(),
        "mfu": None,
        "note": ("router_fleet harness with observability/"
                 "fleetobs.py FleetCollector scraping every "
                 "member's /metrics (OpenMetrics) and draining "
                 "/debug/trace-export each second, SLO evaluation "
                 "and fleet /healthz feedback attached, vs the "
                 "identical fleet unscraped. Median of 4 paired "
                 "back-to-back ratios, pair order alternating; "
                 "bar: ≤2% QPS cost — the collector is pull-based "
                 "and off the request path")}


def _leg_autoscaler_soak(peak):
    """The self-healing-fleet drill as a measured claim: a ~6x QPS
    step over a 1-replica fleet with a seeded whole-replica kill
    mid-spike, tiered traffic (gold/standard/best_effort). Headline:
    seconds from SLO breach to SLO recovery with the autoscaler
    closing the loop (bounds 1..3), vs the same spike on a FIXED
    1-replica fleet (no autoscaler, no kill) where the SLO only
    recovers when the spike ends. Also records per-tier outcomes:
    zero gold-tier drops, best-effort shed first.

    Replica capacity is an explicit per-request service time (a
    sleep-based model), NOT device compute: on this 2-core host the
    router stack itself is host-bound at ~50 q/s (see router_fleet),
    so real-model replicas could not show capacity scaling. The leg
    measures the CONTROL LOOP — detection, boot-first scale-up,
    recovery — and the admission tiering, with loadgen in-process."""
    import threading as _th

    from deeplearning4j_tpu import chaos
    from deeplearning4j_tpu.observability.slo import (BurnWindow, SLO,
                                                      SLOMonitor)
    from deeplearning4j_tpu.serving.autoscaler import Autoscaler
    from deeplearning4j_tpu.serving.fleet import ReplicaFleet
    from deeplearning4j_tpu.serving.router import Router
    from tools.loadgen import (LoadGen, parse_profile,
                               parse_tier_mix, tiered_body_fn)

    class DelayModel:
        def __init__(self, delay_s):
            self.delay_s = delay_s

        def output(self, x):
            time.sleep(self.delay_s)
            return np.asarray(x)

    MIX = "gold=0.2,standard=0.5,best_effort=0.3"
    PROFILE = "step:8:48:2"
    DURATION = 14.0

    def run(autoscale, kill_at=None):
        fleet = ReplicaFleet(
            lambda: {"default": DelayModel(0.04)}, n=1,
            server_kwargs=dict(wait_ms=1.0, max_batch_size=1,
                               queue_limit=6)).start()
        router = Router(fleet, probe_interval_s=0.1,
                        probe_timeout_s=0.5, attempt_timeout_s=3.0,
                        request_timeout_s=8.0, hedge_after_s=None,
                        sample_rate=0.0).start()
        slos = SLOMonitor(router.registry, [SLO(
            name="router_p_latency", objective=0.8, threshold_s=0.1,
            metric="router_latency_seconds",
            labels={"route": "/v1/predict"}, window_s=30.0,
            windows=[BurnWindow(short_s=1.5, long_s=4.0,
                                factor=1.5)])],
            min_eval_interval_s=0.2)
        scaler = None
        if autoscale:
            scaler = Autoscaler(
                fleet, router, slos=slos, registry=router.registry,
                min_replicas=1, max_replicas=3,
                tick_interval_s=0.25, queue_high=3.0,
                queue_low=0.25, up_consecutive=2,
                down_consecutive=10_000, up_cooldown_s=1.5,
                down_cooldown_s=60.0).start()
        if kill_at is not None:
            chaos.install({"faults": [
                {"site": "serving.replica", "kind": "kill",
                 "at": [kill_at], "args": {"replica": 0}}]},
                seed=99)
        body = tiered_body_fn(
            lambda i: {"model": "default",
                       "inputs": [[float(i % 7), 1.0]]},
            parse_tier_mix(MIX))
        gen = LoadGen(f"http://127.0.0.1:{router.port}",
                      body_fn=body, concurrency=24,
                      profile=parse_profile(PROFILE),
                      duration_s=DURATION, timeout_s=6.0,
                      max_retries=6, backlog_limit=512)
        marks = {"breach": None, "recover": None}
        t0 = time.monotonic()
        out = {}

        def load():
            out["report"] = gen.run()

        lt = _th.Thread(target=load, daemon=True)
        lt.start()
        try:
            deadline = t0 + DURATION + 30.0
            while time.monotonic() < deadline:
                b = slos.any_breached()
                now = time.monotonic() - t0
                if b and marks["breach"] is None:
                    marks["breach"] = now
                if not b and marks["breach"] is not None:
                    marks["recover"] = now
                    break
                time.sleep(0.1)
            lt.join(timeout=30.0)
            final_replicas = fleet.size()
        finally:
            chaos.uninstall()
            if scaler is not None:
                scaler.stop(wait_retires=False)
            router.stop()
            fleet.stop(drain=False, timeout=2.0)
        rep = out.get("report", {})
        ups = router.registry.get(
            "autoscaler_scale_events_total",
            labels={"direction": "up"})
        return {"breach_s": marks["breach"],
                "recover_s": marks["recover"],
                "recovery_s": (None if None in marks.values()
                               else round(marks["recover"]
                                          - marks["breach"], 2)),
                "scale_ups": 0 if ups is None else int(ups.value),
                "final_replicas": final_replicas,
                "tiers": rep.get("tiers", {}),
                "failed": rep.get("failed"), "ok": rep.get("ok")}

    scaled = run(autoscale=True, kill_at=150)
    fixed = run(autoscale=False)
    if scaled["recovery_s"] is None:
        raise RuntimeError(
            f"autoscaled run never breached+recovered: {scaled}")
    gold = scaled["tiers"].get("gold", {})
    if gold.get("failed", 1) != 0:
        raise RuntimeError(
            f"gold-tier drops under the autoscaled drill: {gold}")
    fixed_rec = fixed["recovery_s"]
    print(f"autoscaler_soak: breach @{scaled['breach_s']:.1f}s, "
          f"recovered in {scaled['recovery_s']:.1f}s "
          f"({scaled['scale_ups']} scale-ups, kill absorbed, gold "
          f"0 dropped); fixed fleet recovery "
          f"{fixed_rec if fixed_rec is not None else '>30'}s",
          file=sys.stderr)
    return {
        "metric": ("autoscaler SLO-recovery time: ~6x QPS step + "
                   "replica SIGKILL mid-spike, fleet bounds 1..3 "
                   "(in-process replicas, 40ms service time, "
                   "tiered load)"),
        "value": scaled["recovery_s"], "unit": "seconds",
        "baseline": fixed_rec,
        "vs_baseline": (None if not fixed_rec else round(
            fixed_rec / scaled["recovery_s"], 3)),
        "scale_ups": scaled["scale_ups"],
        "final_replicas": scaled["final_replicas"],
        "gold_outcomes": scaled["tiers"].get("gold"),
        "standard_outcomes": scaled["tiers"].get("standard"),
        "best_effort_outcomes": scaled["tiers"].get("best_effort"),
        "fixed_fleet_tiers": fixed["tiers"],
        "host_cpus": os.cpu_count(),
        "mfu": None,
        "note": ("value: breach->recovery seconds with the "
                 "autoscaler closing the loop (step:8:48:2 q/s at "
                 "t=2s, seeded serving.replica kill at request "
                 "ordinal 150 mid-spike; SLO = 80% of "
                 "/v1/predict under 100ms, 1.5s/4s burn windows). "
                 "baseline: the same step on a FIXED 1-replica "
                 "fleet (no kill) — it exits breach too, but only "
                 "by mass-shedding (fast 429s dilute the latency "
                 "objective): see fixed_fleet_tiers — dozens of "
                 "standard/best_effort requests dropped outright "
                 "and even gold pays sheds+retries, vs zero gold "
                 "and zero standard drops with the autoscaler. "
                 "Replicas are sleep-based 40ms-service-time "
                 "models behind real ModelServer/Router HTTP: the "
                 "2-core host is router-bound (router_fleet), so "
                 "the leg measures the control loop + tier "
                 "admission, not hardware scale-out. The drill "
                 "requires ZERO gold failures")}


def _leg_rollout_soak(peak):
    """The canary-rollout drill as a measured claim, both directions:
    a GOOD candidate (behavior-equivalent retrain) promoted
    fleet-wide through the SLO gate, and a BAD candidate
    (NaN-poisoned via a seeded `serving.rollout` `bad_version`
    fault) detected by shadow scoring and automatically rolled
    back. 4 in-process replicas behind the real Router/collector
    stack under live gold/standard/best_effort load. Headlines:
    good-canary time-to-promoted and bad-canary
    time-to-detected-and-rolled-back (status `started_unix` →
    `finished_unix`), with ZERO gold drops in both runs, capacity
    never below 4, and exactly one incident bundle from the bad
    run. Like autoscaler_soak this measures the CONTROL LOOP, not
    device compute."""
    import json as _json
    import shutil
    import tempfile
    import threading as _th
    import urllib.request

    from deeplearning4j_tpu import chaos
    from deeplearning4j_tpu.observability.fleetobs import \
        FleetCollector
    from deeplearning4j_tpu.serving.fleet import UP, ReplicaFleet
    from deeplearning4j_tpu.serving.router import Router
    from deeplearning4j_tpu.serving.rollout import RolloutController

    class EchoModel:
        def output(self, x):
            return np.asarray(x, dtype=np.float32) * 2.0

    TIERS = ("gold", "standard", "best_effort")

    def post(base, body):
        req = urllib.request.Request(
            base + "/v1/predict",
            data=_json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=10.0) as r:
                return r.status, _json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, {}
        except Exception:
            return 0, {}

    def run(bad, inc_dir, seed=23):
        fleet = ReplicaFleet(
            lambda: {"default": EchoModel()}, n=4,
            server_kwargs=dict(wait_ms=1.0, max_batch_size=8,
                               queue_limit=64)).start()
        router = Router(fleet, probe_interval_s=0.05,
                        probe_timeout_s=0.5, attempt_timeout_s=2.0,
                        request_timeout_s=10.0, hedge_after_s=None,
                        sample_rate=1.0).start()
        col = FleetCollector(fleet=fleet, router=router,
                             interval_s=0.25,
                             incident_min_interval_s=0.0,
                             incident_dir=inc_dir).start()
        rc = RolloutController(
            fleet, router,
            candidate_factory=lambda: {"default": EchoModel()},
            collector=col, canary_weight=0.25, shadow_sample=0.5,
            min_requests=40, warmup_requests=10,
            min_shadow_compared=10, gate_poll_s=0.1,
            # wide open: on this 1-2 core host a freshly-booted
            # canary's scheduling jitter can trip any tight ratio —
            # the leg times the control loop; the bad candidate is
            # caught by shadow scoring, which is load-independent
            drain_timeout_s=5.0, max_p99_ratio=50.0)
        if bad:
            chaos.install({"faults": [
                {"site": "serving.rollout", "kind": "bad_version",
                 "at": [1]}]}, seed=seed)
        base = f"http://127.0.0.1:{router.port}"
        counts = {t: {"ok": 0, "dropped": 0} for t in TIERS}
        stop = _th.Event()
        mincap = [10**9]

        def drive(tier):
            i = 0
            while not stop.is_set():
                i += 1
                st, _b = post(base, {"model": "default",
                                     "inputs": [[float(i % 5)]],
                                     "tier": tier})
                counts[tier]["ok" if st == 200
                             else "dropped"] += 1
                mincap[0] = min(mincap[0], sum(
                    1 for r in fleet.snapshot()
                    if r.fleet_state == UP))
                time.sleep(0.004)

        threads = [_th.Thread(target=drive, args=(t,), daemon=True)
                   for t in TIERS]
        out = {}

        def roll():
            out["status"] = rc.run()

        rt = _th.Thread(target=roll, daemon=True)
        try:
            for t in threads:
                t.start()
            time.sleep(1.0)       # incumbent evidence before start
            rt.start()
            rt.join(timeout=120.0)
            if rt.is_alive():
                rc.abort("bench watchdog")
                rt.join(timeout=30.0)
            time.sleep(0.5)       # let in-flight drain into counts
            versions = sorted(fleet.versions().values())
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10.0)
            chaos.uninstall()
            col.stop()
            router.stop()
            fleet.stop(drain=False, timeout=5.0)
        st = out.get("status") or {}
        elapsed = (None if not st.get("finished_unix")
                   else round(st["finished_unix"]
                              - st["started_unix"], 2))
        incidents = sorted(
            d for d in os.listdir(inc_dir)
            if d.startswith("incident-"))
        return {"status": st, "elapsed_s": elapsed,
                "tiers": counts, "min_capacity": mincap[0],
                "versions": versions, "incidents": incidents}

    tmp_good = tempfile.mkdtemp(prefix="bench-rollout-good-")
    tmp_bad = tempfile.mkdtemp(prefix="bench-rollout-bad-")
    try:
        good = run(bad=False, inc_dir=tmp_good)
        bad = run(bad=True, inc_dir=tmp_bad)
        for name, r in (("good", good), ("bad", bad)):
            if r["tiers"]["gold"]["dropped"] != 0:
                raise RuntimeError(
                    f"gold drops in the {name} rollout: {r}")
            if r["min_capacity"] < 4:
                raise RuntimeError(
                    f"capacity dipped below N in {name}: {r}")
        if good["status"].get("outcome") != "promoted":
            raise RuntimeError(f"good canary not promoted: {good}")
        if set(good["versions"]) != {2}:
            raise RuntimeError(
                f"good rollout left mixed versions: {good}")
        if bad["status"].get("outcome") != "rolled_back":
            raise RuntimeError(f"bad canary not rolled back: {bad}")
        if set(bad["versions"]) != {1}:
            raise RuntimeError(
                f"bad rollout left candidate replicas: {bad}")
        if len(bad["incidents"]) != 1:
            raise RuntimeError(
                f"expected exactly one incident: {bad['incidents']}")
        gate = bad["status"].get("last_gate")
    finally:
        shutil.rmtree(tmp_good, ignore_errors=True)
        shutil.rmtree(tmp_bad, ignore_errors=True)
    print(f"rollout_soak: good canary promoted fleet-wide in "
          f"{good['elapsed_s']}s; bad canary caught by gate "
          f"'{gate}' and rolled back in {bad['elapsed_s']}s "
          f"(one incident, zero gold drops both runs)",
          file=sys.stderr)
    return {
        "metric": ("canary rollout control loop: bad-candidate "
                   "(seeded serving.rollout bad_version NaN "
                   "poison) detect->rollback time, 4 in-process "
                   "replicas under tiered load"),
        "value": bad["elapsed_s"], "unit": "seconds",
        "good_promotion_s": good["elapsed_s"],
        "bad_gate": gate,
        "good_gold_outcomes": good["tiers"]["gold"],
        "bad_gold_outcomes": bad["tiers"]["gold"],
        "good_holds": good["status"].get("holds"),
        "incidents": len(bad["incidents"]),
        "host_cpus": os.cpu_count(),
        "mfu": None,
        "note": ("value: start->rolled-back seconds for a "
                 "candidate whose outputs are NaN-poisoned by the "
                 "seeded serving.rollout fault — caught by shadow "
                 "scoring (gate in bad_gate), auto-rolled-back to "
                 "4/4 incumbent with exactly one incident bundle. "
                 "good_promotion_s: start->promoted seconds for a "
                 "behavior-equivalent candidate through the full "
                 "canary->expanding ladder (comparative windowed "
                 "SLO gate against the incumbent cohort). Both "
                 "runs under live gold/standard/best_effort load: "
                 "ZERO gold drops required, UP capacity never "
                 "below 4 (boot-successor-first replaces). "
                 "Like autoscaler_soak, this measures the control "
                 "loop on loopback HTTP, not device compute")}


DECODE_STEPS = 128
DECODE_CAP = 256
MASKED_ATTN_SHAPE = (4, 4096, 8, 64)     # B, T, H, D
MASKED_ATTN_BURST = 100                  # chained steps per burst


def _leg_transformer_decode(peak):
    """Streaming decode for the transformer-LM config: the jitted
    fixed-capacity KV-cache session (models/streaming.py) vs the
    eager concat-cache rnn_time_step path — same contract (parity
    tested in tests/), one XLA dispatch per token vs a Python op
    stream, O(t) vs O(pos) cache traffic per step (round-4 verdict
    weak #7)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import (MultiLayerNetwork,
                                    NeuralNetConfiguration, dtypes)
    from deeplearning4j_tpu.nn.conf import updaters
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (
        EmbeddingSequenceLayer, RnnOutputLayer, TransformerEncoderLayer)

    b = (NeuralNetConfiguration.builder().set_seed(0)
         .updater(updaters.adam(1e-3)).list()
         .layer(EmbeddingSequenceLayer(n_in=LM_V, n_out=LM_D)))
    for _ in range(LM_L):
        b = b.layer(TransformerEncoderLayer(n_heads=LM_H, causal=True))
    conf = (b.layer(RnnOutputLayer(n_out=LM_V, loss="mcxent"))
            .set_input_type(InputType.recurrent(LM_V, DECODE_CAP))
            .build())
    with dtypes.policy_scope(dtypes.tpu_bf16()):
        net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    # fixed id stream (not sampled from the model): keeps every step
    # device-side with no per-token host sync; the cache carry is the
    # cross-step data dependency, so the tunnel cannot dedupe steps
    ids = rng.integers(0, LM_V, (DECODE_STEPS, LM_B, 1)).astype(
        "float32")

    sess = net.streaming_session(capacity=DECODE_CAP, batch=LM_B,
                                 dtype=jnp.bfloat16)
    h = sess.step(ids[0])               # compile the t=1 executable
    float(jnp.sum(h))

    bounded_ctr = [0]

    def m_bounded():
        # drift the id stream per burst: a repeated burst would replay
        # byte-identical (executable, content) calls, which the tunnel
        # runtime can serve memoized (~0s) — same discipline as the
        # fused window below
        bounded_ctr[0] += 1
        ids_b = (ids + bounded_ctr[0]) % LM_V
        sess.reset()
        t0 = time.perf_counter()
        for s in range(DECODE_STEPS):
            h = sess.step(ids_b[s])
        float(jnp.sum(h))               # host fetch = end-of-burst sync
        return time.perf_counter() - t0

    # few eager steps: each token-step is DOZENS of un-jitted op
    # dispatches through the tunnel (~10-130 ms each) — the baseline
    # only needs enough steps for a stable per-token rate, and the
    # short history already flatters it
    eager_steps = 6
    net.rnn_clear_previous_state()
    h = net.rnn_time_step(ids[0])       # warm the eager op caches
    float(jnp.sum(h))

    def m_eager():
        net.rnn_clear_previous_state()
        t0 = time.perf_counter()
        for s in range(eager_steps):
            h = net.rnn_time_step(ids[s])
        float(jnp.sum(h))
        return time.perf_counter() - t0

    dt_b, dt_e = _interleave(m_bounded, m_eager, repeats=3)
    rate_b = DECODE_STEPS * LM_B / dt_b
    rate_e = eager_steps * LM_B / dt_e

    # FUSED decode: the whole generation is ONE lax.scan program —
    # a single dispatch replaces DECODE_STEPS of them (greedy
    # sampling included), which is where the dispatch-bound decode
    # regime actually wants to live on a tunnel'd chip. Tunnel
    # discipline: the prompt CONTENT changes per burst (the runtime
    # memoizes by (executable, input content) — a constant prompt
    # with deterministic greedy decode would repeat byte-identical
    # calls that time as ~0), and the fused/bounded ratio comes from
    # alternating bursts within ONE window.
    fused_ctr = [0]
    sess.reset()
    gen_ids = sess.generate(np.zeros((LM_B, 1), np.float32),
                            DECODE_STEPS, fused=True)   # compile
    float(jnp.sum(gen_ids))

    def m_fused():
        fused_ctr[0] += 1
        prompt = np.full((LM_B, 1), fused_ctr[0] % LM_V, np.float32)
        sess.reset()
        t0 = time.perf_counter()
        out = sess.generate(prompt, DECODE_STEPS, fused=True)
        float(jnp.sum(out))
        return time.perf_counter() - t0

    dt_b2, dt_f = _interleave(m_bounded, m_fused, repeats=3)
    rate_f = DECODE_STEPS * LM_B / dt_f
    fused_vs_bounded = dt_b2 / dt_f
    print(f"transformer decode: bounded-cache {rate_b:.0f} tok/s, "
          f"eager rnn_time_step {rate_e:.0f} tok/s "
          f"({rate_b / rate_e:.1f}x); FUSED scan generate "
          f"{rate_f:.0f} tok/s ({fused_vs_bounded:.1f}x bounded)",
          file=sys.stderr)
    return {
        "metric": (f"Transformer-LM streaming decode (B={LM_B}, "
                   f"d={LM_D}, L={LM_L}, heads={LM_H}, vocab {LM_V}, "
                   f"cap {DECODE_CAP}, bf16 cache)"),
        "value": round(rate_b, 0), "unit": "tokens/sec/chip",
        "baseline": round(rate_e, 0),
        "vs_baseline": round(rate_b / rate_e, 3),
        "fused_scan_tokens_per_sec": round(rate_f, 0),
        "fused_vs_bounded": round(fused_vs_bounded, 3),
        "mfu": None,
        "note": (f"value: jitted fixed-capacity KV-cache session, "
                 f"{DECODE_STEPS} single-token steps; baseline: "
                 f"eager concat-cache rnn_time_step over its FIRST "
                 f"{eager_steps} tokens (short history flatters it — "
                 f"its per-step cost grows with position); "
                 f"fused_scan = generate(fused=True): the whole "
                 f"{DECODE_STEPS}-token greedy decode as ONE XLA "
                 f"program (single dispatch). Parity of all paths "
                 f"is asserted in tests/test_native_and_kernels.py")}


PAGED_V, PAGED_D, PAGED_L, PAGED_H = 256, 128, 2, 4
PAGED_SLOTS = 8
PAGED_CAP = 160
PAGED_PS = 16                 # tokens per KV page
PAGED_POOL = 20               # fixed-memory pool for the slot-count leg
PAGED_STEPS = 96
PAGED_PROMPT = 64
SPEC_K = 8
SPEC_TOKENS = 96


def _paged_lm(seed, width, layers, heads):
    from deeplearning4j_tpu import (MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf import updaters
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (
        EmbeddingSequenceLayer, RnnOutputLayer, TransformerEncoderLayer)
    b = (NeuralNetConfiguration.builder().set_seed(seed)
         .updater(updaters.adam(1e-3)).list()
         .layer(EmbeddingSequenceLayer(n_in=PAGED_V, n_out=width)))
    for _ in range(layers):
        b = b.layer(TransformerEncoderLayer(n_heads=heads,
                                            causal=True))
    conf = (b.layer(RnnOutputLayer(n_out=PAGED_V, loss="mcxent"))
            .set_input_type(InputType.recurrent(PAGED_V, PAGED_CAP))
            .build())
    return MultiLayerNetwork(conf).init()


def _leg_transformer_decode_paged(peak):
    """The decode fast path end to end: (a) paged-KV slot decode vs
    the dense per-slot session at batch N (same math, page-table
    gather — greedy parity is tested in tests/test_decode_paged.py),
    (b) prefix-cache TTFT on a repeated prompt vs cold prefill
    through ContinuousBatcher, (c) draft-model speculative decode vs
    vanilla greedy, and (d) the memory story: concurrent slots at a
    FIXED KV budget, paged vs the dense bucket limit."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.speculative import (
        SpeculativeDecoder)
    from deeplearning4j_tpu.serving.continuous import ContinuousBatcher

    net = _paged_lm(0, PAGED_D, PAGED_L, PAGED_H)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, PAGED_V,
                       (PAGED_STEPS, PAGED_SLOTS, 1, 1)).astype(
                           np.float32)
    active = np.ones((PAGED_SLOTS,), bool)

    # ---- (a) dense vs paged slot-step decode at batch N ----
    dense = net.slot_streaming_session(capacity=PAGED_CAP,
                                       slots=PAGED_SLOTS)
    paged = net.paged_slot_streaming_session(
        capacity=PAGED_CAP, slots=PAGED_SLOTS, page_size=PAGED_PS)

    def _bind_all(sess):
        for s in range(PAGED_SLOTS):
            sess.bind(s, sess.reserve([1], PAGED_STEPS + 2))

    _bind_all(paged)
    float(jnp.sum(dense.step_slots(ids[0], active)))   # compile
    float(jnp.sum(paged.step_slots(ids[0], active)))
    drift = [0]

    def _measure(sess, is_paged):
        def m():
            # drift the id stream per burst (tunnel memoization
            # discipline, same as transformer_decode)
            drift[0] += 1
            ids_b = (ids + drift[0]) % PAGED_V
            if is_paged:
                sess.release_all()
                _bind_all(sess)
            else:
                sess.reset()
            t0 = time.perf_counter()
            for s in range(PAGED_STEPS):
                h = sess.step_slots(ids_b[s], active)
            float(jnp.sum(h))
            return time.perf_counter() - t0
        return m

    dt_p, dt_d = _interleave(_measure(paged, True),
                             _measure(dense, False), repeats=3)
    rate_p = PAGED_STEPS * PAGED_SLOTS / dt_p
    rate_d = PAGED_STEPS * PAGED_SLOTS / dt_d

    # ---- (b) prefix-cache TTFT through the batcher ----
    cb = ContinuousBatcher(net, slots=4, capacity=PAGED_CAP,
                           kv_mode="paged", page_size=PAGED_PS,
                           name="bench_paged")
    try:
        warm = rng.integers(1, PAGED_V, (PAGED_PROMPT,))
        cb.generate(warm, 1)               # compile + worker warmup
        prompt = rng.integers(1, PAGED_V, (PAGED_PROMPT,))
        t0 = time.perf_counter()
        cb.generate(prompt, 1)
        ttft_cold = time.perf_counter() - t0
        ttft_hit = float("inf")
        for _ in range(3):                 # prefix registered at
            t0 = time.perf_counter()       # first completion
            cb.generate(prompt, 1)
            ttft_hit = min(ttft_hit, time.perf_counter() - t0)
        prefix_hits = cb.session.prefix_cache.hits_total
    finally:
        cb.shutdown(drain=False)

    # ---- (c) speculative decode vs vanilla greedy ----
    draft = _paged_lm(7, 32, 1, 2)
    spec_tiny = SpeculativeDecoder(net, draft, k=SPEC_K,
                                   capacity=PAGED_CAP)
    spec_self = SpeculativeDecoder(net, net, k=SPEC_K,
                                   capacity=PAGED_CAP)
    vanilla = net.streaming_session(capacity=PAGED_CAP, batch=1)
    sp = rng.integers(1, PAGED_V, (1, 8))
    spec_tiny.generate(sp, SPEC_TOKENS)    # compile
    spec_self.generate(sp, SPEC_TOKENS)
    vanilla.reset()
    vanilla.generate(sp.astype(np.float32), SPEC_TOKENS)
    sctr = [0]

    def _m_spec(dec):
        def m():
            sctr[0] += 1
            p = (sp + sctr[0]) % PAGED_V
            t0 = time.perf_counter()
            dec.generate(p, SPEC_TOKENS)
            return time.perf_counter() - t0
        return m

    def _m_vanilla():
        sctr[0] += 1
        p = ((sp + sctr[0]) % PAGED_V).astype(np.float32)
        vanilla.reset()
        t0 = time.perf_counter()
        out = vanilla.generate(p, SPEC_TOKENS)
        float(jnp.sum(out))
        return time.perf_counter() - t0

    dt_self, dt_v = _interleave(_m_spec(spec_self), _m_vanilla,
                                repeats=3)
    dt_tiny, dt_v2 = _interleave(_m_spec(spec_tiny), _m_vanilla,
                                 repeats=3)
    dt_v = min(dt_v, dt_v2)
    rate_spec_self = SPEC_TOKENS / dt_self
    rate_spec_tiny = SPEC_TOKENS / dt_tiny
    rate_vanilla = SPEC_TOKENS / dt_v

    # ---- (d) concurrent slots at a FIXED KV budget ----
    pool_tokens = PAGED_POOL * PAGED_PS
    dense_slot_limit = pool_tokens // PAGED_CAP
    fixed = net.paged_slot_streaming_session(
        capacity=PAGED_CAP, slots=PAGED_SLOTS, page_size=PAGED_PS,
        n_pages=PAGED_POOL)
    from deeplearning4j_tpu.serving.errors import (
        KVPagePoolExhaustedError)
    short = rng.integers(1, PAGED_V, (8,))
    concurrent = 0
    try:
        for s in range(PAGED_SLOTS):
            fixed.bind(s, fixed.reserve(short, 24))   # 2 pages each
            concurrent += 1
    except KVPagePoolExhaustedError:
        pass          # the pool is the bound being measured; any
        # other exception is a real bug and must fail the leg

    print(f"paged decode: paged {rate_p:.0f} tok/s vs dense "
          f"{rate_d:.0f} tok/s at B={PAGED_SLOTS}; TTFT cold "
          f"{ttft_cold * 1e3:.1f} ms vs prefix-hit "
          f"{ttft_hit * 1e3:.1f} ms ({prefix_hits} hits); spec "
          f"self-draft {rate_spec_self:.0f} tok/s / tiny-draft "
          f"{rate_spec_tiny:.0f} (acc "
          f"{spec_tiny.acceptance_rate:.2f}) vs vanilla "
          f"{rate_vanilla:.0f}; {concurrent} concurrent slots vs "
          f"dense limit {dense_slot_limit} at {pool_tokens} tokens "
          f"KV", file=sys.stderr)
    return {
        "metric": (f"transformer_decode_paged: paged-KV continuous "
                   f"decode (B={PAGED_SLOTS} slots, d={PAGED_D}, "
                   f"L={PAGED_L}, heads={PAGED_H}, vocab {PAGED_V}, "
                   f"cap {PAGED_CAP}, page {PAGED_PS})"),
        "value": round(rate_p, 0), "unit": "tokens/sec/chip",
        "baseline": round(rate_d, 0),
        "vs_baseline": round(rate_p / rate_d, 3),
        "ttft_cold_ms": round(ttft_cold * 1e3, 3),
        "ttft_prefix_hit_ms": round(ttft_hit * 1e3, 3),
        "prefix_ttft_speedup": round(ttft_cold / ttft_hit, 3),
        "prefix_cache_hits": prefix_hits,
        "spec_self_draft_tokens_per_sec": round(rate_spec_self, 0),
        "spec_tiny_draft_tokens_per_sec": round(rate_spec_tiny, 0),
        "spec_vanilla_tokens_per_sec": round(rate_vanilla, 0),
        "spec_self_vs_vanilla": round(rate_spec_self / rate_vanilla,
                                      3),
        "spec_tiny_vs_vanilla": round(rate_spec_tiny / rate_vanilla,
                                      3),
        "spec_tiny_acceptance": round(spec_tiny.acceptance_rate, 4),
        "spec_k": SPEC_K,
        "kv_pool_tokens_fixed_mem": pool_tokens,
        "dense_slot_limit_at_fixed_mem": dense_slot_limit,
        "paged_concurrent_slots_at_fixed_mem": concurrent,
        "mfu": None,
        "note": (f"value/baseline: tokens/sec over {PAGED_STEPS} "
                 f"single-token steps with all {PAGED_SLOTS} slots "
                 "active — paged gathers each slot's page table, "
                 "dense indexes a private capacity-row cache (greedy "
                 "tokens bit-identical; tested). TTFT: "
                 "ContinuousBatcher n_tokens=1 request wall time; "
                 f"the prefix-hit path resumes after "
                 f"{PAGED_PROMPT // PAGED_PS} cached pages instead "
                 f"of {PAGED_PROMPT} teacher-forced prefill steps. "
                 "Speculative: self-draft (acceptance 1.0) is the "
                 "machinery ceiling — 2 draft dispatches (feed + "
                 f"fused k={SPEC_K} scan) + 1 chunked verify per "
                 "round replace k single-token dispatches; the "
                 "tiny-draft row is an UNTRAINED draft, so its "
                 "acceptance (~1/vocab) makes it a slowdown — a "
                 "distilled draft lands between the two rows. "
                 "Slot-count row: at a fixed "
                 "pool of KV memory the dense session can host only "
                 "floor(mem/capacity) slots; paged binds pages per "
                 "request's actual need")}


def _leg_flash_attention_masked(peak):
    """Variable-length batch at T=4096 through the kv-mask-aware
    Pallas kernels (fwd+bwd) vs (a) exact masked attention — the
    fallback a maskless kernel forces — and (b) the unmasked kernel —
    the masking overhead. Records the COMPONENTS.md claim as an
    artifact (round-4 verdict weak #6)."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.ops.attention import (_exact_masked,
                                                  flash_attention)
    B, T, H, D = MASKED_ATTN_SHAPE
    rngk = jax.random.PRNGKey(0)
    q = jax.random.normal(rngk, (B, T, H, D), jnp.float32)
    # ragged real lengths (1/4 .. full): the shapes stay static, the
    # mask carries the raggedness — the TPU-native variable-length
    # contract
    lens = tuple(T * (i + 1) // B for i in range(B))
    mask = jnp.asarray(
        np.arange(T)[None, :] < np.asarray(lens)[:, None],
        jnp.float32)

    def mk(fn):
        # chain grad(q) into the next input: identical in-flight calls
        # dedupe through the tunnel and time as ~0 (see
        # _leg_flash_attention)
        g = jax.jit(jax.grad(
            lambda x: jnp.sum((fn(x, x, x)
                               * mask[:, :, None, None]) ** 2)))
        float(jnp.sum(g(q)))
        burst = MASKED_ATTN_BURST

        def measure():
            a = q
            t0 = time.perf_counter()
            for _ in range(burst):
                a = g(a)
            float(jnp.sum(a))
            return (time.perf_counter() - t0) / burst
        return measure

    m_masked = mk(lambda a, b, c: flash_attention(a, b, c,
                                                  kv_mask=mask))
    m_exact = mk(lambda a, b, c: _exact_masked(a, b, c, mask, False))
    m_unmasked = mk(lambda a, b, c: flash_attention(a, b, c))
    # two interleave windows, both anchored on the masked kernel so
    # each ratio comes from alternating bursts within one window
    dt_m, dt_e = _interleave(m_masked, m_exact, repeats=3)
    dt_m2, dt_u = _interleave(m_masked, m_unmasked, repeats=3)
    toks = float(sum(lens))            # real (unpadded) tokens
    attn_flops = 14 * T * T * D * B * H
    if peak:
        _check_plausible(attn_flops / min(dt_m, dt_e) / peak,
                         "masked flash attention")
        _check_plausible(attn_flops / min(dt_m2, dt_u) / peak,
                         "masked flash (unmasked window)")
    print(f"masked flash T={T} ragged fwd+bwd: "
          f"{toks/dt_m:.0f} real tok/s; vs exact masked "
          f"{dt_e/dt_m:.2f}x; vs unmasked kernel "
          f"{dt_u/dt_m2:.3f}x", file=sys.stderr)
    return {
        "metric": ("masked flash attention fwd+bwd, ragged batch "
                   f"(B={B}, T={T}, lens={list(lens)}, H={H}, D={D}, "
                   "f32)"),
        "value": round(toks / dt_m, 0), "unit": "real tokens/sec",
        "baseline": round(toks / dt_e, 0),
        "vs_baseline": round(dt_e / dt_m, 3),
        "vs_exact_masked": round(dt_e / dt_m, 3),
        "vs_unmasked_kernel": round(dt_u / dt_m2, 3),
        "mfu": None,
        "note": ("baseline = exact masked attention (materializes "
                 "TxT with -inf bias) — what variable-length batches "
                 "fall back to without kv-mask-aware kernels; "
                 "vs_unmasked_kernel isolates the mask operand's "
                 "overhead (1.0 = free). Throughput counts REAL "
                 "(unpadded) tokens only")}


CKPT_HIDDEN = 1024        # ~4.3M params -> ~17MB of f32 to zip
CKPT_LAYERS = 4
CKPT_SAVES = 6
PS_EPOCH_CAP = 40         # per-variant epoch bound for the PS leg


def _leg_checkpoint_async(peak):
    """Robustness-overhead leg: train-thread BLOCKED ms per
    checkpoint save, sync vs the async background writer — the number
    behind the preemption-tolerance claim that checkpointing is off
    the critical path. Sync saves pay snapshot + npz + DEFLATE + zip
    + rename on the train thread; async saves pay only the
    device→host snapshot and the writer handoff. The async p99 comes
    from the checkpoint_write_seconds{phase="blocked"} histogram
    itself (reset before the async phase so it holds async samples
    only), so the committed number is the same instrument operators
    scrape."""
    import shutil
    import tempfile

    from deeplearning4j_tpu import (MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf import updaters
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                   OutputLayer)
    from deeplearning4j_tpu.observability.registry import REGISTRY
    from deeplearning4j_tpu.train.fault_tolerance import ElasticTrainer

    b = (NeuralNetConfiguration.builder().set_seed(0)
         .updater(updaters.adam(1e-3)).list())
    for _ in range(CKPT_LAYERS):
        b = b.layer(DenseLayer(n_out=CKPT_HIDDEN, activation="relu"))
    conf = (b.layer(OutputLayer(n_out=16))
            .set_input_type(InputType.feed_forward(CKPT_HIDDEN))
            .build())
    net = MultiLayerNetwork(conf).init()
    zip_mb = net.num_params() * 4 / 1e6
    root = tempfile.mkdtemp(prefix="bench_ckpt_")
    try:
        sync = ElasticTrainer(net, os.path.join(root, "sync"), keep=2,
                              handle_sigterm=False)
        sync_s = []
        for _ in range(CKPT_SAVES):
            net.iteration_count += 1
            t0 = time.perf_counter()
            sync.save_checkpoint()
            sync_s.append(time.perf_counter() - t0)
        # fresh histograms: the p99 reported below must be async-only
        for phase in ("blocked", "total"):
            REGISTRY.unregister("checkpoint_write_seconds",
                                {"phase": phase})
        asy = ElasticTrainer(net, os.path.join(root, "async"), keep=2,
                             handle_sigterm=False,
                             async_checkpoint=True)
        blocked, total = [], []
        for _ in range(CKPT_SAVES):
            net.iteration_count += 1
            t0 = time.perf_counter()
            asy.save_checkpoint()
            blocked.append(time.perf_counter() - t0)
            # barrier per save so total measures one clean write (no
            # coalescing in the measured window)
            asy.checkpoint_barrier()
            total.append(time.perf_counter() - t0)
        asy.close()
        hist = REGISTRY.histogram("checkpoint_write_seconds",
                                  labels={"phase": "blocked"})
        blocked_p99_ms = hist.snapshot()["p99"] * 1e3
    finally:
        shutil.rmtree(root, ignore_errors=True)
    sync_ms = sorted(sync_s)[len(sync_s) // 2] * 1e3
    async_total_ms = sorted(total)[len(total) // 2] * 1e3
    ratio = blocked_p99_ms / sync_ms if sync_ms else None
    print(f"checkpoint_async: sync {sync_ms:.1f} ms/save blocked; "
          f"async blocked p99 {blocked_p99_ms:.2f} ms "
          f"(total {async_total_ms:.1f} ms), zip ~{zip_mb:.0f}MB, "
          f"blocked/sync {ratio:.3f}", file=sys.stderr)
    return {
        "metric": (f"checkpoint save train-thread blocked time "
                   f"(async writer, ~{zip_mb:.0f}MB of f32 params, "
                   f"p99 of {CKPT_SAVES} saves)"),
        "value": round(blocked_p99_ms, 3), "unit": "ms/save",
        "baseline": None, "vs_baseline": None,
        "sync_blocked_ms_per_save": round(sync_ms, 2),
        "async_blocked_ms_p99": round(blocked_p99_ms, 3),
        "async_total_ms_per_save": round(async_total_ms, 2),
        "blocked_over_sync": None if ratio is None
        else round(ratio, 4),
        "note": ("sync saves serialize+zip+rename on the train "
                 "thread; async saves pay device->host snapshot + "
                 "writer handoff only (the writer does the rest off "
                 "thread, one in-flight write, newest-supersedes "
                 "coalescing). Acceptance bar: blocked p99 under 10% "
                 "of the sync write time (blocked_over_sync < 0.1). "
                 "p99 read from the "
                 "checkpoint_write_seconds{phase=blocked} histogram "
                 "after an async-only reset — the operators' own "
                 "instrument, not a bench-local stopwatch")}


def _leg_ps_async_training(peak):
    """Async parameter-server leg: time-to-target-loss for 3 async
    PS workers (int8+EF compressed pushes) vs a synchronous
    single-process SGD loop over the SAME batches, model and rate —
    plus the staleness-vs-accuracy frontier (max_staleness 0 / 4 /
    16 / unbounded). The target is self-calibrating: 80% of the loss
    drop the sync loop achieves inside the epoch cap, so the leg
    measures wall-clock to equivalent progress, not steps. Workers
    are threads (the jitted grad step releases the GIL) against an
    in-process server — the same wire protocol and staleness
    machinery as the multi-process ``train-ps`` CLI, minus process
    spawn noise."""
    import threading

    import jax
    import numpy as np

    from deeplearning4j_tpu import (MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.data.dataset import DataSet
    from deeplearning4j_tpu.nn.conf import updaters
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                   OutputLayer)
    from deeplearning4j_tpu.parallel.paramserver import (
        ParameterServer, PSClient, PSWorker)

    N_IN, N_OUT, HIDDEN = 8, 3, 16
    N_BATCHES, BATCH = 24, 16
    LR, EPOCH_CAP, WORKERS = 0.2, PS_EPOCH_CAP, 3

    def net(seed=0):
        conf = (NeuralNetConfiguration.builder().set_seed(seed)
                .updater(updaters.sgd(LR)).list()
                .layer(DenseLayer(n_out=HIDDEN, activation="relu"))
                .layer(OutputLayer(n_out=N_OUT))
                .set_input_type(InputType.feed_forward(N_IN))
                .build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(0)
    batches = []
    for _ in range(N_BATCHES):
        c = rng.integers(0, N_OUT, BATCH)
        x = (rng.normal(size=(BATCH, N_IN))
             + c[:, None] * 1.5).astype(np.float32)
        batches.append(DataSet(x, np.eye(N_OUT,
                                         dtype=np.float32)[c]))

    ev_model = net(seed=0)
    ev_batches = [ev_model._batch_tuple(ds) for ds in batches]

    @jax.jit
    def _ev_one(params, batch):
        loss, _ = ev_model._loss(params, ev_model.state, batch,
                                 None, training=False)
        return loss

    def eval_loss(params):
        return float(np.mean([_ev_one(params, b)
                              for b in ev_batches]))

    # -- synchronous baseline: plain SGD, exact (uncompressed) grads
    sync = net(seed=0)
    state = sync.state

    def loss_fn(p, batch, r):
        loss, _ = sync._loss(p, state, batch, r, training=True)
        return loss

    vg = jax.jit(jax.value_and_grad(loss_fn))
    params = sync.params
    init_loss = eval_loss(params)
    key = sync._rng_key
    vg(params, ev_batches[0], key)     # compile outside the clock
    t0 = time.perf_counter()
    sync_curve = []
    for epoch in range(EPOCH_CAP):
        for i, b in enumerate(ev_batches):
            _, g = vg(params, b, jax.random.fold_in(
                key, epoch * N_BATCHES + i))
            params = jax.tree_util.tree_map(
                lambda p, gg: p - LR * gg, params, g)
        sync_curve.append((time.perf_counter() - t0,
                           eval_loss(params)))
    sync_total = time.perf_counter() - t0
    sync_final = sync_curve[-1][1]
    target = init_loss - 0.8 * (init_loss - sync_final)

    def first_crossing(curve):
        for t, l in curve:
            if l <= target:
                return t
        return None

    sync_ttl = first_crossing(sync_curve)

    # -- async PS: workers run to the cap; a monitor thread records
    # the first target crossing from the server's own params
    def run_ps(max_staleness):
        m0 = net(seed=0)
        server = ParameterServer(m0.params, lr=LR,
                                 max_staleness=max_staleness).start()
        crossed = [None]
        stop = threading.Event()
        t0 = time.perf_counter()

        def monitor():
            while not stop.wait(0.05):
                if crossed[0] is None \
                        and eval_loss(server.params_tree()) <= target:
                    crossed[0] = time.perf_counter() - t0

        mon = threading.Thread(target=monitor, name="ps-bench-mon",
                               daemon=True)
        stats = [None] * WORKERS

        def work(i):
            model = m0 if i == 0 else net(seed=i)
            client = PSClient(server.address)
            try:
                stats[i] = PSWorker(model, client,
                                    name=f"ps-bench-{i}").run(
                    batches[i::WORKERS], epochs=EPOCH_CAP)
            finally:
                client.close()

        threads = [threading.Thread(target=work, args=(i,),
                                    name=f"ps-bench-{i}",
                                    daemon=True)
                   for i in range(WORKERS)]
        mon.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join(600)
        total = time.perf_counter() - t0
        stop.set()
        mon.join(10)
        final = eval_loss(server.params_tree())
        if crossed[0] is None and final <= target:
            crossed[0] = total      # crossed between monitor ticks
        st = dict(server.stats)
        server.stop()
        return {"max_staleness": max_staleness,
                "time_to_target_s": None if crossed[0] is None
                else round(crossed[0], 3),
                "total_s": round(total, 3),
                "final_loss": round(final, 4),
                "stale_rejects": st["pushes_stale"],
                "pushes_applied": st["pushes_applied"]}

    frontier = [run_ps(ms) for ms in (0, 4, 16, None)]
    headline = next(f for f in frontier if f["max_staleness"] == 4)
    ttl = headline["time_to_target_s"]
    print("ps_async_training: sync time-to-target "
          f"{sync_ttl and round(sync_ttl, 2)}s "
          f"(final {sync_final:.4f}); async s=4 time-to-target "
          f"{ttl}s; frontier "
          + ", ".join(f"s={f['max_staleness']}: "
                      f"loss {f['final_loss']} in "
                      f"{f['time_to_target_s']}s"
                      for f in frontier), file=sys.stderr)
    return {
        "metric": (f"async PS time-to-target-loss, {WORKERS} "
                   f"int8+EF workers, max_staleness=4 (target = 80% "
                   f"of the sync loss drop, {N_BATCHES}x{BATCH} "
                   "synthetic 3-class batches)"),
        "value": ttl, "unit": "s",
        "baseline": None if sync_ttl is None else round(sync_ttl, 3),
        "vs_baseline": None if not (ttl and sync_ttl)
        else round(sync_ttl / ttl, 3),
        "target_loss": round(target, 4),
        "init_loss": round(init_loss, 4),
        "sync_final_loss": round(sync_final, 4),
        "sync_total_s": round(sync_total, 3),
        "staleness_frontier": frontier,
        "note": ("vs_baseline is sync/async time-to-target "
                 "(>1 = async reaches equivalent progress faster). "
                 "The frontier shows the bounded-staleness "
                 "accuracy/speed trade: s=0 serializes pushes "
                 "(stale_rejects climb), unbounded runs free. "
                 "Same server/worker/wire stack as `train-ps`; "
                 "workers are in-process threads so the number "
                 "isolates protocol + staleness cost from process "
                 "spawn noise")}


def _kstep_lenet(c1=4, c2=8, dense=64, seed=0):
    """Scaled-down LeNet for the k-step leg: same stack, channel
    counts shrunk so the per-step device compute sits well under the
    host's per-dispatch overhead — the dispatch-bound regime the
    full-size LeNet occupies on TPU (where ~1 ms of compute meets a
    ~1 ms host round-trip), reproduced on whatever host runs the
    leg."""
    from deeplearning4j_tpu import (MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf import updaters
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (ConvolutionLayer,
                                                   DenseLayer,
                                                   OutputLayer,
                                                   SubsamplingLayer)
    conf = (NeuralNetConfiguration.builder().set_seed(seed)
            .updater(updaters.adam(1e-3)).list()
            .layer(ConvolutionLayer(n_out=c1, kernel=(5, 5),
                                    activation="relu"))
            .layer(SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=c2, kernel=(5, 5),
                                    activation="relu"))
            .layer(SubsamplingLayer(kernel=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=dense, activation="relu"))
            .layer(OutputLayer(n_out=10, loss="mcxent"))
            .set_input_type(InputType.convolutional_flat(28, 28, 1))
            .build())
    return MultiLayerNetwork(conf).init()


def _kstep_batch(batch=8, seed=0):
    from deeplearning4j_tpu.data.dataset import DataSet
    rng = np.random.default_rng(seed)
    x = rng.normal(0, 1, (batch, 784)).astype("float32")
    y = np.eye(10, dtype="float32")[rng.integers(0, 10, batch)]
    return DataSet(x, y)


KSTEP_TOTAL = 384          # logical steps per measured k (div by 64)


def _leg_lenet_kstep(peak):
    """k-step fused training on the dispatch-bound LeNet config:
    steps/sec and per-step jitter at k ∈ {1, 8, 64}, every program
    AOT-warmed so no measurement pays a compile. Each fit_batches
    call is one device dispatch covering k steps; the per-call wall
    time / k is the per-step cost whose spread is the jitter the
    ISSUE's MFU analysis flagged (±20% on the per-step path)."""
    ds = _kstep_batch()
    res = {}
    for k in (1, 8, 64):
        net = _kstep_lenet()
        net.warmup(ds, steps_per_device_call=k)
        batches = [ds] * k
        for _ in range(max(2, 16 // k)):            # warm the loop
            net.fit_batches(batches, steps_per_device_call=k)
        per_step = []
        t0 = time.perf_counter()
        for _ in range(KSTEP_TOTAL // k):
            t1 = time.perf_counter()
            net.fit_batches(batches, steps_per_device_call=k)
            per_step.append((time.perf_counter() - t1) / k)
        dt = time.perf_counter() - t0
        srt = sorted(per_step)
        p50 = srt[len(srt) // 2]
        p95 = srt[min(len(srt) - 1, int(len(srt) * 0.95))]
        res[k] = {"steps_per_sec": KSTEP_TOTAL / dt,
                  "step_ms_p50": p50 * 1e3,
                  "step_ms_p95": p95 * 1e3,
                  "jitter_pct": (p95 - p50) / p50 * 100.0}
        print(f"lenet_kstep k={k}: {res[k]['steps_per_sec']:.0f} "
              f"steps/s, p50 {res[k]['step_ms_p50']:.2f} ms, "
              f"jitter (p95-p50)/p50 {res[k]['jitter_pct']:.0f}%",
              file=sys.stderr)
    out = {
        "metric": ("LeNet k-step fused training, dispatch-bound "
                   "config (c4/c8/d64, batch 8): k=8 one-program "
                   "steps/sec vs per-step dispatch"),
        "value": round(res[8]["steps_per_sec"], 1),
        "unit": "steps/sec",
        "baseline": round(res[1]["steps_per_sec"], 1),
        "vs_baseline": round(res[8]["steps_per_sec"]
                             / res[1]["steps_per_sec"], 3),
        "mfu": None,
        "note": ("k steps fused into one lax.scan device program "
                 "(donated carry), AOT-warmed: the host round-trip "
                 "+ dispatch overhead is paid once per k steps. "
                 "Jitter = (p95-p50)/p50 of per-step wall time; the "
                 "fused path also smooths it because k steps share "
                 "one dispatch."),
    }
    for k, r in res.items():
        out[f"k{k}_steps_per_sec"] = round(r["steps_per_sec"], 1)
        out[f"k{k}_step_ms_p50"] = round(r["step_ms_p50"], 3)
        out[f"k{k}_jitter_pct"] = round(r["jitter_pct"], 1)
    return out


def _leg_aot_warmup(peak):
    """AOT warmup: programs compiled at warmup vs ZERO in the steady
    state (train fit windows + tail, and a serving predict burst over
    every pow2 bucket), plus first-call latency warm vs cold. The
    zero-compile claims are asserted with
    compile_watch.zero_compile_scope — the leg FAILS if the steady
    state compiles."""
    from deeplearning4j_tpu.observability.compile_watch import (
        install_global_watch)
    stats = install_global_watch()
    ds = _kstep_batch()

    # cold: first call traces + compiles (the persistent bench cache
    # may soften this on repeat runs — reported as-is)
    net_cold = _kstep_lenet(seed=1)
    t0 = time.perf_counter()
    net_cold.fit_batches([ds])
    cold_first_s = time.perf_counter() - t0

    # warm: lower().compile() both programs up front, then the first
    # call dispatches a ready executable
    net_warm = _kstep_lenet(seed=1)
    mark_w = stats.mark()
    rep = net_warm.warmup(ds, steps_per_device_call=8)
    warmup_stats = stats.summary(mark_w)
    warmup_secs = sum(rep.values())
    t0 = time.perf_counter()
    net_warm.fit_batches([ds])
    warm_first_s = time.perf_counter() - t0

    # steady state: fused windows + a 3-batch tail, zero compiles
    with stats.zero_compile_scope("aot_warmup train steady state"):
        for _ in range(5):
            net_warm.fit_batches([ds] * 8, steps_per_device_call=8)
            net_warm.fit_batches([ds] * 3, steps_per_device_call=8)

    # serving: warm every pow2 bucket, then a mixed-size burst
    from deeplearning4j_tpu.serving.http import ModelServer
    from deeplearning4j_tpu.serving.registry import ModelRegistry
    reg = ModelRegistry()
    reg.register("default", _kstep_lenet(seed=2))
    cold_srv = ModelServer(reg, max_batch_size=8)
    sched, _ = cold_srv.scheduler_for("default")
    x1 = np.zeros((1, 784), np.float32)
    t0 = time.perf_counter()
    sched.predict(x1, timeout=120)
    serve_cold_first_s = time.perf_counter() - t0
    cold_srv.stop(drain=False)

    reg2 = ModelRegistry()
    reg2.register("default", _kstep_lenet(seed=2))
    warm_srv = ModelServer(reg2, max_batch_size=8)
    warm_srv.warmup(generate=False)
    sched2, _ = warm_srv.scheduler_for("default")
    t0 = time.perf_counter()
    sched2.predict(x1, timeout=120)
    serve_warm_first_s = time.perf_counter() - t0
    with stats.zero_compile_scope("aot_warmup serve burst"):
        for n in (1, 2, 3, 5, 8, 7, 4, 1):
            sched2.predict(np.zeros((n, 784), np.float32),
                           timeout=120)
    warm_srv.stop(drain=False)

    print(f"aot_warmup: train first call cold {cold_first_s*1e3:.0f} "
          f"ms vs warm {warm_first_s*1e3:.1f} ms; serve first "
          f"request cold {serve_cold_first_s*1e3:.0f} ms vs warm "
          f"{serve_warm_first_s*1e3:.1f} ms; steady-state compiles "
          "0+0 (asserted)", file=sys.stderr)
    return {
        "metric": ("AOT warmup: first train-step latency, warmed "
                   "(jit().lower(shapes).compile() at startup) vs "
                   "cold first call"),
        "value": round(warm_first_s * 1e3, 2), "unit": "ms",
        "baseline": round(cold_first_s * 1e3, 2),
        "vs_baseline": round(warm_first_s / cold_first_s, 4),
        "mfu": None,
        "programs_compiled_at_warmup": sorted(rep),
        "warmup_compile_secs": round(warmup_secs, 3),
        "warmup_backend_compiles":
            warmup_stats["backend_compiles"],
        "steady_state_backend_compiles": 0,
        "serve_first_request_cold_ms":
            round(serve_cold_first_s * 1e3, 2),
        "serve_first_request_warm_ms":
            round(serve_warm_first_s * 1e3, 2),
        "note": ("steady_state_backend_compiles is ASSERTED zero by "
                 "compile_watch.zero_compile_scope over 5 fused "
                 "windows + k=1 tails AND a mixed-batch-size predict "
                 "burst over pre-warmed pow2 buckets; the leg fails "
                 "if anything compiles. Cold numbers can be softened "
                 "by the persistent XLA cache on repeat bench runs."),
    }


def _leg_multichip_dp_scaling(peak):
    """Mesh-spec sharded training throughput: dp=1 vs dp=2 at k=1 vs
    k=8 on the forced-host-device CPU mesh (the README recipe), every
    program AOT-warmed. Runs in a NESTED subprocess so the forced
    8-device XLA flag applies regardless of how this leg process's
    backend was initialized. On this 2-core host dp=2 shares the same
    two cores, so the leg proves the sharded program path (one SPMD
    program per window, zero steady-state compiles) rather than real
    scaling — the speedup column is the k-fusion win on a mesh."""
    import subprocess
    script = r"""
import json, os, sys, time
import numpy as np
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from deeplearning4j_tpu import (MultiLayerNetwork,
                                NeuralNetConfiguration)
from deeplearning4j_tpu.nn.conf import updaters
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.data.dataset import DataSet
from deeplearning4j_tpu.observability.compile_watch import (
    install_global_watch)

def net(seed=0):
    conf = (NeuralNetConfiguration.builder().set_seed(seed)
            .updater(updaters.adam(1e-3)).list()
            .layer(DenseLayer(n_out=64, activation="relu"))
            .layer(DenseLayer(n_out=64, activation="relu"))
            .layer(OutputLayer(n_out=10))
            .set_input_type(InputType.feed_forward(32)).build())
    return MultiLayerNetwork(conf).init()

rng = np.random.default_rng(0)
x = rng.normal(size=(64, 32)).astype(np.float32)
y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 64)]
ds = DataSet(x, y)
TOTAL = 192
stats = install_global_watch()
out = {}
for dp in (1, 2):
    for k in (1, 8):
        m = net(seed=1)
        m.use_mesh(f"dp={dp}")
        m.warmup(ds, steps_per_device_call=k)
        batches = [ds] * k
        for _ in range(max(2, 16 // k)):            # warm the loop
            m.fit_batches(batches, steps_per_device_call=k)
        t0 = time.perf_counter()
        with stats.zero_compile_scope(f"dp={dp} k={k} steady"):
            for _ in range(TOTAL // k):
                m.fit_batches(batches, steps_per_device_call=k)
        dt = time.perf_counter() - t0
        out[f"dp{dp}_k{k}_steps_per_sec"] = round(TOTAL / dt, 1)
print(json.dumps(out))
"""
    here = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8"
                        ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          cwd=here, capture_output=True, text=True,
                          timeout=600)
    if proc.returncode != 0:
        raise RuntimeError(
            f"multichip subprocess failed: {proc.stderr[-2000:]}")
    res = json.loads(proc.stdout.strip().splitlines()[-1])
    print("multichip_dp_scaling: "
          + ", ".join(f"{k}={v}" for k, v in res.items()),
          file=sys.stderr)
    return {
        "metric": ("mesh-spec sharded training steps/sec, 3-layer "
                   "MLP (d64/d64/out10, batch 64) on the forced "
                   "8-host-device CPU mesh: dp=2 fused k=8 windows "
                   "vs per-step"),
        "value": res["dp2_k8_steps_per_sec"],
        "unit": "steps/sec",
        "baseline": res["dp2_k1_steps_per_sec"],
        "vs_baseline": round(res["dp2_k8_steps_per_sec"]
                             / res["dp2_k1_steps_per_sec"], 3),
        "mfu": None,
        **res,
        "note": ("fit(mesh_spec='dp=N') + steps_per_device_call=k: "
                 "one SPMD device program per fused window, AOT-"
                 "warmed, zero steady-state compiles ASSERTED per "
                 "config (the leg fails if anything compiles). "
                 "This 2-core host runs every forced 'device' on "
                 "the same two cores, so dp=2 cannot beat dp=1 "
                 "here — the leg pins the sharded-path overhead and "
                 "the k-fusion multiplier on a mesh; real dp "
                 "scaling needs real chips."),
    }


DISAGG_V, DISAGG_D, DISAGG_H = 64, 32, 2
DISAGG_CAP, DISAGG_PS = 96, 16
DISAGG_PROMPT, DISAGG_TOKENS = 32, 8
DISAGG_REQUESTS = 160
DISAGG_CONC = 4


def _leg_disagg_kv_routing(peak):
    """KV-aware (prefix-fingerprint) routing vs the affinity-only
    router over a 4-replica in-process fleet under a
    ``--dup-ratio 0.5`` duplicate-prompt generate mix: the KV-aware
    router sends a repeated prompt to the replica whose prefix cache
    already holds it, so the fleet-wide prefix-hit ratio rises and
    the duplicate population's TTFT collapses to the hit path.
    Everything here shares one process (replicas + router + GIL), so
    the honest read is the RATIO between the two router modes in the
    same harness, plus the hit-vs-cold TTFT split scraped from the
    replicas' own ``serving_ttft_seconds{population=...}``
    histograms."""
    import subprocess
    import urllib.request

    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf import updaters
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (
        EmbeddingSequenceLayer, RnnOutputLayer,
        TransformerEncoderLayer)
    from deeplearning4j_tpu.serving.fleet import ReplicaFleet
    from deeplearning4j_tpu.serving.router import Router
    from tools.loadgen import scrape_ttft_populations

    def lm():
        conf = (NeuralNetConfiguration.builder().set_seed(0)
                .updater(updaters.adam(1e-3)).list()
                .layer(EmbeddingSequenceLayer(n_in=DISAGG_V,
                                              n_out=DISAGG_D))
                .layer(TransformerEncoderLayer(n_heads=DISAGG_H,
                                               causal=True))
                .layer(RnnOutputLayer(n_out=DISAGG_V, loss="mcxent"))
                .set_input_type(InputType.recurrent(DISAGG_V,
                                                    DISAGG_CAP))
                .build())
        return MultiLayerNetwork(conf).init()

    def factory():
        return {"default": lm()}

    def loadgen(port, total):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.loadgen",
             "--url", f"http://127.0.0.1:{port}",
             "--mode", "generate", "--dup-ratio", "0.5",
             "--prompt-len", str(DISAGG_PROMPT),
             "--n-tokens", str(DISAGG_TOKENS),
             "--vocab", str(DISAGG_V),
             "--concurrency", str(DISAGG_CONC),
             "--total", str(total),
             "--timeout", "60", "--retries", "2",
             "--metrics-url", "off"],
            capture_output=True, text=True, timeout=600,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if not proc.stdout.strip():
            raise RuntimeError(
                f"loadgen exited {proc.returncode} with no report; "
                f"stderr: {proc.stderr[-800:]}")
        return json.loads(proc.stdout)

    def run(kv_routing):
        fleet = ReplicaFleet(
            factory, n=4,
            server_kwargs=dict(slots=4, capacity=DISAGG_CAP,
                               page_size=DISAGG_PS)).start()
        router = Router(fleet, probe_interval_s=0.2,
                        hedge_after_s=None, sample_rate=0.0,
                        request_timeout_s=60.0,
                        kv_routing=kv_routing).start()
        try:
            # warm every replica's compiled decode DIRECTLY (not via
            # the router) with a sub-page prompt: 8 tokens < one
            # 16-token page, so nothing enters any prefix cache and
            # the measured mix starts cold on every replica
            warm = json.dumps({"model": "default",
                               "prompt": list(range(1, 9)),
                               "n_tokens": 2}).encode()
            for r in fleet.snapshot():
                req = urllib.request.Request(
                    r.url + "/v1/generate", data=warm,
                    headers={"Content-Type": "application/json"})
                urllib.request.urlopen(req, timeout=120).read()
            rep = loadgen(router.port, DISAGG_REQUESTS)
            if rep.get("failed"):
                raise RuntimeError(
                    f"disagg_kv_routing dropped requests: "
                    f"{rep['failed']} ({rep.get('errors')})")
            hits = sum(
                s["prefix_cache_hits_total"]
                for s in router.load_signals())
            ttft = scrape_ttft_populations(
                [r.url for r in fleet.snapshot()], timeout_s=10)
            kv_routed = router._kv_routed.value
        finally:
            router.stop()
            fleet.stop(drain=False, timeout=5.0)
        return {"report": rep, "hits": hits, "ttft": ttft,
                "hit_ratio": hits / max(1, rep["ok"]),
                "kv_routed": kv_routed}

    kv = run(True)
    aff = run(False)
    print(f"disagg_kv_routing: KV-aware hit ratio "
          f"{kv['hit_ratio']:.2f} ({int(kv['hits'])}/"
          f"{kv['report']['ok']}, {int(kv['kv_routed'])} "
          f"prefix-routed) vs affinity-only {aff['hit_ratio']:.2f} "
          f"({int(aff['hits'])}/{aff['report']['ok']}); TTFT hit "
          f"p50 {kv['ttft']['prefix_hit']['p50']:.1f} ms vs cold "
          f"p50 {kv['ttft']['cold']['p50']:.1f} ms (baseline cold "
          f"p50 {aff['ttft']['cold']['p50']:.1f} ms)",
          file=sys.stderr)
    return {
        "metric": (f"disagg_kv_routing: fleet-wide prefix-hit "
                   f"ratio under a dup-ratio 0.5 generate mix "
                   f"(4 in-process replicas, prompt "
                   f"{DISAGG_PROMPT}, page {DISAGG_PS}, "
                   f"{DISAGG_REQUESTS} requests) — KV-aware "
                   f"router vs affinity-only"),
        "value": round(kv["hit_ratio"], 3),
        "unit": "prefix-hit ratio",
        "baseline": round(aff["hit_ratio"], 3),
        "vs_baseline": round(
            kv["hit_ratio"] / max(1e-9, aff["hit_ratio"]), 3),
        "kv_routed_requests": int(kv["kv_routed"]),
        "ttft_ms": {
            "kv_hit_p50": kv["ttft"]["prefix_hit"]["p50"],
            "kv_hit_p99": kv["ttft"]["prefix_hit"]["p99"],
            "kv_cold_p50": kv["ttft"]["cold"]["p50"],
            "kv_cold_p99": kv["ttft"]["cold"]["p99"],
            "affinity_hit_p50": aff["ttft"]["prefix_hit"]["p50"],
            "affinity_cold_p50": aff["ttft"]["cold"]["p50"]},
        "hit_counts": {"kv": int(kv["hits"]),
                       "affinity": int(aff["hits"]),
                       "requests": kv["report"]["ok"]},
        "client_latency_ms": {
            "kv_p50": kv["report"]["latency_ms"]["p50"],
            "affinity_p50": aff["report"]["latency_ms"]["p50"]},
        "note": ("replicas, router and their GIL share one "
                 "process on the 2-core host: read the two router "
                 "modes as a controlled A/B, not absolute "
                 "throughput"),
    }


# (name, fn, warm-cache wall estimate sec). Order = priority: the five
# BASELINE.md configs first (VGG before the informational flash leg —
# round-2 lost config 4 to the wall clock with the legs the other way).
RETR_N, RETR_DIM, RETR_CLUSTERS = 8192, 64, 64
RETR_NLIST, RETR_K = 64, 10
RETR_CONC, RETR_QUERIES = 8, 512


def _leg_retrieval_serving(peak):
    """Retrieval serving, two claims. (1) The recall@k-vs-throughput
    FRONTIER: brute-force exact search vs IVF at nprobe 1/4/16
    through the batched search backend, p50/p99 per config, with
    ZERO steady-state compiles asserted after warmup (the pow2
    bucketing + snapshot-constant gather width make the shapes
    static). (2) The SOAK: a 4-replica subprocess fleet serving
    mixed predict + search traffic through the router, one replica
    SIGKILLed mid-run by a seeded chaos fault — zero dropped search
    requests and recall@10 >= 0.9 on the IVF path, measured by
    loadgen's client-side oracle."""
    import subprocess
    import tempfile
    import urllib.request

    from deeplearning4j_tpu import (MultiLayerNetwork,
                                    NeuralNetConfiguration, chaos)
    from deeplearning4j_tpu.nn.conf import updaters
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import (DenseLayer,
                                                   OutputLayer)
    from deeplearning4j_tpu.observability.compile_watch import (
        install_global_watch)
    from deeplearning4j_tpu.retrieval import (BruteForceIndex,
                                              IVFIndex)
    from deeplearning4j_tpu.serving.fleet import ReplicaFleet
    from deeplearning4j_tpu.serving.metrics import ServingMetrics
    from deeplearning4j_tpu.serving.retrieval_backend import (
        RetrievalService)
    from deeplearning4j_tpu.serving.router import Router
    from deeplearning4j_tpu.util.model_serializer import write_model
    from tools.loadgen import SearchWorkload

    stats = install_global_watch()
    rng = np.random.default_rng(0)
    centers = rng.normal(size=(RETR_CLUSTERS, RETR_DIM))
    assign = rng.integers(0, RETR_CLUSTERS, size=RETR_N)
    vectors = (centers[assign]
               + 0.15 * rng.standard_normal((RETR_N, RETR_DIM))
               ).astype(np.float32)
    ids = np.arange(RETR_N)
    wl = SearchWorkload(vectors, ids=ids, k=RETR_K,
                        metric="cosine", pool=256, seed=1)

    def run_config(label, index, nprobe):
        svc = RetrievalService(index, metrics=ServingMetrics(),
                               max_batch_size=32, wait_ms=1.0)
        try:
            # warm every pow2 batch bucket the closed loop can form
            svc.warmup(ks=(RETR_K,), nprobes=(nprobe,),
                       batch_sizes=(1, 2, 4, 8))
            lock = threading.Lock()
            lat, hits = [], [0, 0]
            per = RETR_QUERIES // RETR_CONC

            def worker(wid):
                for j in range(per):
                    i = wid * per + j
                    r = min(wl.rank_of(i), len(wl.queries) - 1)
                    t0 = time.perf_counter()
                    rids, _ = svc.search(wl.queries[r], k=RETR_K,
                                         nprobe=nprobe, timeout=60.0)
                    dt = time.perf_counter() - t0
                    got = {int(x) for x in rids[0] if x >= 0}
                    h = len(got & wl._oracle[r])
                    with lock:
                        lat.append(dt)
                        hits[0] += h
                        hits[1] += RETR_K

            t0 = time.perf_counter()
            with stats.zero_compile_scope(
                    f"retrieval {label} steady state"):
                threads = [threading.Thread(target=worker, args=(w,),
                                            daemon=True)
                           for w in range(RETR_CONC)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
            wall = time.perf_counter() - t0
            lat.sort()
            return {"config": label, "nprobe": nprobe,
                    "qps": round(len(lat) / wall, 1),
                    "p50_ms": round(lat[len(lat) // 2] * 1e3, 3),
                    "p99_ms": round(
                        lat[min(len(lat) - 1,
                                int(len(lat) * 0.99))] * 1e3, 3),
                    "recall_at_10": round(hits[0] / hits[1], 4),
                    "steady_state_backend_compiles": 0}
        finally:
            svc.close(drain=False)

    brute = BruteForceIndex(RETR_DIM, metric="cosine")
    brute.add(ids, vectors)
    ivf = IVFIndex(RETR_DIM, nlist=RETR_NLIST, metric="cosine")
    ivf.build(ids, vectors)
    frontier = [run_config("brute_force", brute, None)]
    for nprobe in (1, 4, 16):
        frontier.append(run_config(f"ivf_nprobe{nprobe}", ivf,
                                   nprobe))
    for row in frontier:
        print(f"retrieval frontier: {row['config']} "
              f"{row['qps']:.0f} q/s p50 {row['p50_ms']:.1f} ms "
              f"p99 {row['p99_ms']:.1f} ms recall@10 "
              f"{row['recall_at_10']:.3f}", file=sys.stderr)

    # ---- soak: 4 subprocess replicas, mixed traffic, SIGKILL ----
    feat, hidden, classes = 16, 32, 4
    conf = (NeuralNetConfiguration.builder().set_seed(0)
            .updater(updaters.adam(1e-3)).list()
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(OutputLayer(n_out=classes, loss="mcxent"))
            .set_input_type(InputType.feed_forward(feat)).build())
    tmp = tempfile.mkdtemp(prefix="bench_retr_")
    model_zip = os.path.join(tmp, "mlp.zip")
    write_model(MultiLayerNetwork(conf).init(), model_zip)
    corpus = (f"random:n=4096,dim=32,seed=11,clusters="
              f"{RETR_CLUSTERS // 2}")

    def loadgen(router_port, mode, total, out):
        cmd = [sys.executable, "-m", "tools.loadgen",
               "--url", f"http://127.0.0.1:{router_port}",
               "--concurrency", "8", "--total", str(total),
               "--timeout", "30", "--retries", "3"]
        if mode == "search":
            cmd += ["--mode", "search", "--corpus", corpus,
                    "--k", str(RETR_K), "--metric", "cosine"]
        else:
            cmd += ["--features", str(feat)]
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=300,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if not proc.stdout.strip():
            raise RuntimeError(
                f"loadgen {mode} exited {proc.returncode} with no "
                f"report; stderr: {proc.stderr[-800:]}")
        out[mode] = json.loads(proc.stdout)

    fleet = ReplicaFleet(
        model_specs=[f"default={model_zip}"], n=4, base_port=18350,
        extra_args=["--index", corpus, "--index-kind", "ivf",
                    "--nlist", str(RETR_NLIST // 2),
                    "--nprobe", "8"]).start()
    router = Router(fleet, probe_interval_s=0.25, hedge_after_s=None,
                    sample_rate=0.0).start()
    try:
        deadline = time.monotonic() + 180.0
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{router.port}/healthz",
                        timeout=5.0) as r:
                    if json.load(r).get("eligible") == 4:
                        break
            except OSError:
                pass
            time.sleep(0.25)
        else:
            raise RuntimeError("retrieval fleet never became ready")
        # warmup both routes outside the measured window
        warm: dict = {}
        loadgen(router.port, "predict", 128, warm)
        loadgen(router.port, "search", 128, warm)
        chaos.install({"faults": [
            {"site": "serving.replica", "kind": "kill",
             "at": [200], "args": {"replica": 0}}]}, seed=1234)
        reports: dict = {}
        threads = [threading.Thread(
            target=loadgen,
            args=(router.port, mode, 400, reports), daemon=True)
            for mode in ("predict", "search")]
        for t in threads:
            t.start()
        for t in threads:
            t.join(300.0)
    finally:
        chaos.uninstall()
        router.stop()
        fleet.stop(drain=False, timeout=5.0)
    sr, pr = reports["search"], reports["predict"]
    soak_recall = sr["search"]["recall_at_k"]
    if sr["failed"] or pr["failed"]:
        raise RuntimeError(
            f"retrieval soak dropped requests: search="
            f"{sr['failed']} ({sr['errors']}) predict="
            f"{pr['failed']} ({pr['errors']})")
    if soak_recall is None or soak_recall < 0.9:
        raise RuntimeError(
            f"retrieval soak recall@10 {soak_recall} < 0.9")
    print(f"retrieval soak: search {sr['achieved_qps']:.0f} q/s "
          f"p99 {sr['latency_ms']['p99']:.1f} ms recall@10 "
          f"{soak_recall:.3f}, predict {pr['achieved_qps']:.0f} "
          f"q/s — 0 dropped through SIGKILL", file=sys.stderr)
    ivf16 = next(r for r in frontier
                 if r["config"] == "ivf_nprobe16")
    return {
        "metric": (f"retrieval serving: IVF nprobe=16 search QPS "
                   f"through the batched backend ({RETR_CONC} "
                   f"closed-loop clients, {RETR_N} vectors, dim "
                   f"{RETR_DIM}, k={RETR_K})"),
        "value": ivf16["qps"], "unit": "queries/sec",
        "baseline": frontier[0]["qps"],
        "vs_baseline": round(ivf16["qps"]
                             / max(frontier[0]["qps"], 1e-9), 3),
        "recall_qps_frontier": frontier,
        "soak": {
            "replicas": 4, "sigkill_at_ordinal": 200,
            "search_qps": sr["achieved_qps"],
            "search_p99_ms": sr["latency_ms"]["p99"],
            "search_dropped": sr["failed"],
            "search_retries": sr["retries"],
            "predict_qps": pr["achieved_qps"],
            "predict_dropped": pr["failed"],
            "recall_at_10": soak_recall},
        "host_cpus": os.cpu_count(),
        "mfu": None,
        "note": ("frontier: recall@10 vs QPS for brute-force exact "
                 "search (the baseline) vs IVF at nprobe 1/4/16, "
                 "one in-process RetrievalService per config, "
                 "steady-state compiles ASSERTED zero after warmup "
                 "(zero_compile_scope fails the leg otherwise); "
                 "clustered gaussian corpus. soak: 4 subprocess "
                 "replicas each hosting the same IVF index behind "
                 "the router, concurrent predict + Zipf search "
                 "loadgens, replica 0 SIGKILLed by a seeded "
                 "serving.replica chaos fault mid-run — zero "
                 "dropped requests on either route and recall@10 "
                 ">= 0.9 are asserted, recall measured client-side "
                 "against the exact oracle. Loopback HTTP, one "
                 "host: QPS measures the stack, not scale-out")}


_LEGS = [
    ("resnet_f32", _leg_resnet_f32, 420),
    ("resnet_bf16", _leg_resnet_bf16, 420),
    # config 4 runs EARLY: it is the heaviest leg and the tunnel
    # degrades under sustained load — round 2 (and two round-3 runs)
    # lost this number by scheduling it late
    ("vgg16_import", _leg_vgg16_import, 600),
    ("lenet", _leg_lenet, 180),
    ("char_rnn", _leg_char_rnn, 240),
    ("transformer_lm", _leg_transformer_lm, 300),
    ("flash_attention", _leg_flash_attention, 300),
    ("flash_attention_masked", _leg_flash_attention_masked, 300),
    ("transformer_decode", _leg_transformer_decode, 300),
    # small config (CPU-feasible): paged vs dense decode, prefix-hit
    # TTFT, speculative vs vanilla, fixed-memory slot count
    ("transformer_decode_paged", _leg_transformer_decode_paged, 300),
    ("serving_throughput", _leg_serving_throughput, 180),
    # 480s: its ResNet executable (n_classes=10) is NOT covered by
    # the other ResNet legs' compile cache — cold tunnel compile ~5min
    ("resnet_native_etl", _leg_resnet_native_etl, 480),
    # host-side (no device step in the loop): cheap, runs last
    ("checkpoint_async", _leg_checkpoint_async, 120),
    # CPU-dominated (tiny MLP, loopback TCP PS + worker threads):
    # time-to-target-loss vs sync + the staleness frontier
    ("ps_async_training", _leg_ps_async_training, 240),
    # CPU-dominated (tiny models, dispatch path): cheap, runs last
    ("lenet_kstep", _leg_lenet_kstep, 240),
    # nested subprocess with the forced 8-host-device mesh: cheap,
    # CPU-only by construction
    ("multichip_dp_scaling", _leg_multichip_dp_scaling, 240),
    ("aot_warmup", _leg_aot_warmup, 180),
    # CPU-dominated (tiny MLP, scheduler hot path): cheap, runs last
    ("tracing_overhead", _leg_tracing_overhead, 180),
    # CPU-dominated (loopback HTTP, tiny MLP replicas): cheap
    ("router_fleet", _leg_router_fleet, 240),
    # CPU-dominated (loopback HTTP, tiny transformer replicas):
    # the KV-aware vs affinity-only router A/B
    ("disagg_kv_routing", _leg_disagg_kv_routing, 300),
    # CPU-dominated (loopback HTTP, subprocess replicas): collector
    # scrape on/off A/B over the router_fleet harness
    ("observability_overhead", _leg_observability_overhead, 240),
    # CPU-dominated (sleep-based replicas, control-loop timing):
    # cheap, runs last
    ("autoscaler_soak", _leg_autoscaler_soak, 240),
    # CPU-dominated (in-process replicas, control-loop timing):
    # good-canary promotion + bad-canary detect->rollback
    ("rollout_soak", _leg_rollout_soak, 240),
    # CPU-dominated (matmul top-k on tiny corpora, loopback HTTP):
    # the recall-vs-QPS frontier + SIGKILL search soak
    ("retrieval_serving", _leg_retrieval_serving, 300),
]

# every runnable --leg (the burst headline rides outside the ordered
# full-leg list: the orchestrator schedules it explicitly, first)
_LEG_FNS = {**{n: f for n, f, _ in _LEGS},
            "resnet_burst": _leg_resnet_burst}
BURST_ESTIMATE = 300        # warm-cache: seconds; cold: one compile


def _setup_xla_cache():
    """Persistent XLA compilation cache — the tunnel'd AOT compile of
    the ResNet50 train step alone is ~5 min cold; with the cache a
    repeat run's legs compile in seconds. Must run in EVERY leg
    subprocess (config is per-process), before first backend use."""
    import jax
    cache_dir = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".bench_cache",
        "xla")
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)


def _pin_cpu_if_requested():
    """JAX_PLATFORMS=cpu must hold even though the axon plugin re-pins
    the platform at import time — a wedged tunnel would otherwise hang
    CPU-pinned rehearsals/smokes at first backend use (the
    tests/conftest.py + examples idiom)."""
    from deeplearning4j_tpu.util.platform import pin_cpu_platform
    pin_cpu_platform()


def _run_leg_inprocess(name):
    _pin_cpu_if_requested()
    if os.environ.get("BENCH_REHEARSE_HANG") == "1":
        # degraded-tunnel rehearsal: the leg subprocess hangs forever,
        # exactly like a wedged axon terminal. The orchestrator's
        # watchdog must still produce the stdout artifact + rc 0.
        time.sleep(1e9)
    _setup_xla_cache()
    # hook jax.monitoring BEFORE first backend use so every compile
    # in the leg is counted: compile_cache_hit answers the round-5
    # question 'did the 441s timeout hide a cold compile?' with data
    compile_stats = None
    try:
        from deeplearning4j_tpu.observability.compile_watch import (
            install_global_watch)
        compile_stats = install_global_watch()
    except Exception as e:
        print(f"{name}: compile accounting unavailable: {e}",
              file=sys.stderr)
    peak, _ = _peak_flops()
    fn = _LEG_FNS[name]
    try:
        cfg = fn(peak)
    except ImportError as e:
        # missing optional dependency (keras/h5py): a clean SKIP, not
        # a transient failure — rc 3 tells the orchestrator not to
        # burn a cooldown + retry on it
        print(f"{name}: dependency unavailable: {e}", file=sys.stderr)
        raise SystemExit(3)
    if compile_stats is not None:
        s = compile_stats.summary()
        cfg["compile_cache_hit"] = s["cache_hit"]
        cfg["compile_stats"] = {
            k: s[k] for k in ("backend_compiles", "compile_secs",
                              "cache_requests",
                              "persistent_cache_hits")}
        print(f"{name}: compile_cache_hit={s['cache_hit']} "
              f"(backend_compiles={s['backend_compiles']}, "
              f"{s['compile_secs']:.1f}s compiling, persistent hits "
              f"{s['persistent_cache_hits']}/{s['cache_requests']} "
              "requests)", file=sys.stderr)
    print(json.dumps(cfg), flush=True)


# ---------------------------------------------------------------------------
# orchestrator hardening — two of four driver runs ended rc=124 with no
# stdout line (round 2, round 4: tunnel degraded, leg timeouts +
# cooldowns ate the budget, the driver wall-killed the process while a
# fallback was still compiling). The contract is inverted now: a
# watchdog GUARANTEES one stdout JSON line and exit 0 before a hard
# internal deadline set under the driver budget, whatever the tunnel
# does. Freshly measured if the headline leg finished; else the last
# committed BENCH_DETAIL headline tagged "stale": true.
# ---------------------------------------------------------------------------

_ACTIVE_CHILD = {"proc": None}
_HEADLINE_PRINTED = threading.Event()
_EMIT_LOCK = threading.Lock()

_PLACEHOLDER_HEADLINE = {
    "metric": "ResNet50 train throughput (batch 128, 224x224, f32)",
    "value": 0.0, "unit": "images/sec/chip", "vs_baseline": None}

# best headline available if the full leg never lands, upgraded as
# the run progresses: committed-stale -> fresh burst. One holder so
# the watchdog and the main path cannot emit different fallbacks.
_FALLBACK = {"cfg": None, "stale": True}


def _emit_headline(cfg, stale=False):
    """The ONE stdout line the driver parses. Idempotent under the
    main-path/watchdog race: the lock makes test-and-set atomic, so
    exactly one caller emits."""
    with _EMIT_LOCK:
        if _HEADLINE_PRINTED.is_set():
            return
        _HEADLINE_PRINTED.set()
    out = {"metric": cfg["metric"], "value": cfg["value"],
           "unit": cfg["unit"], "vs_baseline": cfg.get("vs_baseline")}
    if cfg.get("mfu") is not None:
        out["mfu"] = cfg["mfu"]
    if cfg.get("burst"):
        out["burst"] = True
    if cfg.get("compile_cache_hit") is not None:
        out["compile_cache_hit"] = cfg["compile_cache_hit"]
    if stale:
        out["stale"] = True
        out["stale_note"] = ("tunnel degraded this run; value is the "
                             "last committed BENCH_DETAIL.json "
                             "headline, not freshly measured")
    print(json.dumps(out), flush=True)


def _emit_best_fallback():
    """No full freshly-measured headline is coming: emit the best we
    hold — the fresh short-burst number if the burst leg landed
    (stale=False: it WAS measured this run), else the committed stale
    headline, else the explicit zero-value placeholder."""
    cfg = _FALLBACK["cfg"]
    _emit_headline(cfg if cfg is not None else _PLACEHOLDER_HEADLINE,
                   stale=_FALLBACK["stale"] or cfg is None)


def _cheapest_first(legs):
    """Degraded-tunnel ordering (round-5 verdict next #1c): after the
    first headline timeout, run the remaining legs cheapest-first so
    *something* fresh survives the budget instead of the two most
    expensive legs eating it."""
    return sorted(legs, key=lambda t: t[2])


def _kill_child():
    p = _ACTIVE_CHILD.get("proc")
    if p is not None and p.poll() is None:
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            pass


def _hard_deadline(budget):
    """Seconds of runway before the watchdog must fire. Leaves the
    larger of 60s / 20% of budget as headroom under the driver's wall
    clock (the driver's true budget is >= BENCH_BUDGET_SECONDS; the
    env default is deliberately conservative). Floor of 5s keeps
    tiny-budget rehearsals meaningful."""
    return max(5.0, budget - max(60.0, 0.2 * budget))


def _start_watchdog(t_start, budget, flush):
    """Daemon thread: at the hard deadline, emit the best headline we
    have (fresh if the main path already printed, else the freshest
    _FALLBACK — burst-or-stale), kill any in-flight leg subprocess
    (an orphan holding the driver's stderr pipe would block its read
    past our exit), and _exit(0). os._exit skips atexit/interpreter
    teardown — that is the point: a wedged tunnel client cannot veto
    process death."""
    deadline = t_start + _hard_deadline(budget)

    def run():
        while True:
            left = deadline - time.perf_counter()
            if left <= 0:
                break
            time.sleep(min(left, 1.0))
        if not _HEADLINE_PRINTED.is_set():
            _emit_best_fallback()
        _kill_child()
        try:
            flush()
        except Exception:
            pass
        sys.stderr.write("watchdog: hard deadline reached — exiting "
                         "0 with the emitted headline\n")
        sys.stderr.flush()
        os._exit(0)

    t = threading.Thread(target=run, name="bench-watchdog", daemon=True)
    t.start()
    return deadline


def main():
    if "--leg" in sys.argv:
        _run_leg_inprocess(sys.argv[sys.argv.index("--leg") + 1])
        return

    headline_only = ("--headline-only" in sys.argv
                     or os.environ.get("BENCH_HEADLINE_ONLY") == "1")
    budget = float(os.environ.get("BENCH_BUDGET_SECONDS", "900"))
    t_start = time.perf_counter()
    import subprocess
    here = os.path.abspath(__file__)
    detail_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_DETAIL.json")
    # snapshot the COMMITTED detail headline NOW, before any flush()
    # overwrites the file — the watchdog's stale fallback (the burst
    # leg upgrades _FALLBACK to a fresh number once it lands)
    try:
        with open(detail_path) as f:
            prev = json.load(f)
        configs = prev.get("configs") or []
        # ONLY headline-config entries qualify (a degraded prior run
        # may have committed cheapest-first legs ahead of configs[-1];
        # promoting e.g. the serving leg to the driver-parsed
        # headline line would corrupt the artifact). Prefer the
        # committed FULL headline over a committed burst.
        heads = [c for c in configs if str(c.get("metric", ""))
                 .startswith("ResNet50 train throughput (batch 128, "
                             "224x224, f32")]
        full = [c for c in heads if not c.get("burst")]
        if full or heads:
            _FALLBACK["cfg"] = (full or heads)[0]
    except Exception:
        pass

    def noop_flush():
        pass

    # watchdog is armed BEFORE the first backend/tunnel touch: even
    # the device-kind probe can hang on a wedged terminal
    flush_holder = {"fn": noop_flush}
    deadline = _start_watchdog(t_start, budget,
                               lambda: flush_holder["fn"]())

    if os.environ.get("BENCH_REHEARSE_ORCH_HANG") == "1":
        # rehearsal: the orchestrator itself wedges right after arming
        # the watchdog (worst case: even the device probe hangs). The
        # watchdog must still deliver the artifact + rc 0.
        time.sleep(1e9)

    def left_to_deadline():
        return deadline - time.perf_counter()

    # device kind via a SUBPROCESS: the orchestrator must not hold a
    # TPU client itself — on exclusively-locked TPUs (plain TPU VMs,
    # no tunnel) that would lock every --leg subprocess out
    try:
        kind = subprocess.run(
            [sys.executable, "-c",
             "import os, jax\n"
             "if os.environ.get('JAX_PLATFORMS') == 'cpu':\n"
             "    jax.config.update('jax_platforms', 'cpu')\n"
             "print(jax.devices()[0].device_kind)"],
            capture_output=True,
            # tight cap: the probe only feeds the MFU side-metric, and
            # on a wedged tunnel every probe second is headline runway
            # (observed: a 135s probe timeout ate a quarter of the
            # rehearsal budget)
            timeout=max(15, min(90, left_to_deadline() * 0.2)),
            check=True,
        ).stdout.decode().strip().splitlines()[-1]
    except Exception:
        kind = "unknown"
    peak = _peak_for_kind(kind)
    detail = {"device_kind": kind,
              "mfu_note": ("model-FLOPs MFU vs bf16 peak "
                           f"{peak/1e12:.0f} TFLOP/s" if peak else
                           "unknown device; MFU omitted"),
              "mfu_analysis": (
                  "What bounds MFU at the ResNet50 batch-128 224^2 "
                  "config (~9% f32 / ~13% bf16): not framework "
                  "overhead — flax measures the same (vs_baseline "
                  "~1.0), so the ceiling is model-shape x hardware. "
                  "(1) The stem and early stages have 64-256 channels: "
                  "contraction dims below the 128x128 MXU tile leave "
                  "lanes idle (the 7x7/2 stem contracts over just "
                  "3x49=147 values). (2) ~53 BatchNorm+ReLU+residual "
                  "elementwise passes move the full activation set "
                  "through HBM; XLA fuses them into neighbors but the "
                  "conv outputs still round-trip. (3) bf16 halves "
                  "matmul passes (9->13% MFU, 1.44x step speedup) and "
                  "since round 3 the hidden activations ride bf16 too "
                  "(halved elementwise HBM traffic, +1.4% step). "
                  "Round-4 lever probes (measured, 3x10-step bursts, "
                  "bf16): batch 256 -> 1930 img/s vs 1969 at b128 "
                  "(-2%: HBM-bound regime, deeper pipelining buys "
                  "nothing); zero-padding the stem input 3->8 "
                  "channels -> 1856 img/s (-6%: pays 8/3 stem "
                  "FLOPs+traffic, MXU still idle on a 7x7 spatial "
                  "contraction); both together 1978 (+0.5%, noise). "
                  "Conclusion: ResNet50-224 at this batch is "
                  "elementwise-HBM-bound, not a tuning miss — the "
                  "MXU-busy showcase is the transformer-LM config in "
                  "this file (flash kernels, bf16, ~0.42 MFU) and "
                  "VGG16's dense 4096-wide layers."),
              "configs": []}

    flush_lock = threading.Lock()

    def flush():
        # write incrementally after EVERY leg — a driver wall-kill
        # mid-leg must not lose captured configs. Never clobber the
        # committed file with an EMPTY run: the watchdog's next-round
        # stale fallback lives there. Locked: the watchdog thread
        # also flushes at the deadline, and two writers interleaving
        # on the same tmp file would commit corrupt JSON.
        if not detail["configs"]:
            return
        with flush_lock:
            tmp = detail_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(detail, f, indent=2)
            os.replace(tmp, detail_path)

    flush_holder["fn"] = flush

    def _run_leg_once(name, estimate, timeout):
        if timeout < 60:
            print(f"{name} skipped: {timeout:.0f}s timeout too small",
                  file=sys.stderr)
            return "skip"
        p = None
        try:
            # own process GROUP: on timeout or watchdog fire the whole
            # leg tree dies — an orphan holding our inherited stderr
            # pipe would block the driver's read past our exit
            p = subprocess.Popen(
                [sys.executable, here, "--leg", name],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                start_new_session=True)
            _ACTIVE_CHILD["proc"] = p
            try:
                out, err = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                _kill_child()
                try:
                    out, err = p.communicate(timeout=10)
                except Exception:
                    out, err = b"", b""
                sys.stderr.write(err.decode(errors="replace"))
                print(f"{name} leg timed out ({timeout:.0f}s)",
                      file=sys.stderr)
                return None
            sys.stderr.write(err.decode(errors="replace"))
            if p.returncode == 3:       # clean dependency skip
                return "skip"
            if p.returncode != 0:
                print(f"{name} leg failed rc={p.returncode}",
                      file=sys.stderr)
                return None
            line = out.decode().strip().splitlines()[-1]
            return json.loads(line)
        except Exception as e:
            print(f"{name} leg error: {e}", file=sys.stderr)
            return None
        finally:
            if p is not None and p.poll() is None:
                _kill_child()
            _ACTIVE_CHILD["proc"] = None

    def run_leg(name, estimate, headline=False):
        left = left_to_deadline()
        if left < min(estimate, 120):
            print(f"{name} skipped: {left:.0f}s to deadline < leg "
                  f"estimate {estimate}s", file=sys.stderr)
            return None
        # budget-aware from leg one (round-4 failure: two 450s headline
        # attempts + cooldown overran the driver's wall clock). The
        # first attempt may use at most 60% of the runway to the HARD
        # deadline (70% for the headline: the watchdog guarantees the
        # artifact either way, and a cold tunnel compile needs the
        # extra runway more than the retry does), so a failure always
        # leaves room to act on.
        cfg = _run_leg_once(name, estimate,
                            min(left * (0.7 if headline else 0.6),
                                estimate * 2))
        if cfg is None:
            left = left_to_deadline()
            need = 30 + min(estimate, 120)
            if left < need + (30 if headline else 60):
                print(f"{name}: failed and {left:.0f}s to deadline — "
                      "skipping retry", file=sys.stderr)
                return None
            # the tunnel recovers from transient transport failures /
            # degraded-sync episodes within a minute; one retry with a
            # shorter cooldown for the headline (runway is precious)
            cool = 30 if headline else 60
            print(f"{name}: cooling down {cool}s then retrying",
                  file=sys.stderr)
            time.sleep(cool)
            cfg = _run_leg_once(name, estimate,
                                min(left_to_deadline() * 0.8,
                                    estimate * 2))
        return None if cfg == "skip" else cfg

    # BURST first (round-5 verdict next #1a): a <=10-timed-step fresh
    # headline committed before the full legs start, so a degraded
    # tunnel that kills the 420s leg still yields a number measured
    # THIS run. It also warms the persistent XLA cache for the full
    # headline's two executables.
    burst = run_leg("resnet_burst", BURST_ESTIMATE, headline=True)
    if burst is not None:
        detail["configs"].append(burst)
        flush()
        _FALLBACK["cfg"] = burst
        _FALLBACK["stale"] = False      # fresh, just short-burst

    # full headline; fall back to in-process if the subprocess dies
    head = run_leg("resnet_f32", 420, headline=True)
    if head is None and burst is None and left_to_deadline() > 120:
        # last resort: in-process (initializes the backend here — the
        # subprocess legs already failed, so holding the client is
        # moot). Only reached when even the burst failed: with a
        # fresh burst in hand, runway is better spent on cheap legs.
        # The watchdog still guards this: if the compile wedges, the
        # fallback headline goes out at the deadline regardless.
        try:
            _pin_cpu_if_requested()
            _setup_xla_cache()
            # same compile accounting as the subprocess legs: THIS
            # path runs precisely when the tunnel is degraded, where
            # 'did a cold compile eat the budget?' matters most
            cstats = cmark = None
            try:
                from deeplearning4j_tpu.observability.compile_watch \
                    import install_global_watch
                cstats = install_global_watch()
                cmark = cstats.mark()
            except Exception:
                pass
            head = _leg_resnet_f32(peak)
            if cstats is not None:
                s = cstats.summary(since=cmark)
                head["compile_cache_hit"] = s["cache_hit"]
                head["compile_stats"] = {
                    k: s[k] for k in ("backend_compiles",
                                      "compile_secs", "cache_requests",
                                      "persistent_cache_hits")}
        except Exception as e:
            print(f"in-process headline fallback failed: {e}",
                  file=sys.stderr)
            head = None
    if head is not None:
        detail["configs"].insert(0, head)
        flush()
        # the driver consumes stdout's single JSON line — emit it NOW
        # so a timeout in the (informational) extras can't lose it
        _emit_headline(head)
    else:
        # the full headline is not happening; emit the freshest line
        # we hold (burst if it landed, else committed-stale) NOW
        # rather than waiting for the watchdog
        _emit_best_fallback()

    if not headline_only:
        rest = list(_LEGS[1:])
        if head is None:
            # first headline timeout => degraded tunnel: cheapest
            # first so the remaining runway yields the most fresh legs
            rest = _cheapest_first(rest)
            print("headline leg failed - reordering remaining legs "
                  "cheapest-first: "
                  + ", ".join(n for n, _, _ in rest), file=sys.stderr)
        for name, _fn, estimate in rest:
            cfg = run_leg(name, estimate)
            if cfg is not None:
                detail["configs"].append(cfg)
                flush()
    flush()
    if not _HEADLINE_PRINTED.is_set():
        _emit_best_fallback()


if __name__ == "__main__":
    main()
