"""Benchmark harness — prints ONE JSON line with the headline metric.

Current headline: LeNet-MNIST training throughput (images/sec) on the
available chip(s), against the BASELINE.md LeNet config. Will move to
ResNet50/ImageNet images/sec/chip as the zoo fills out (BASELINE.json
north star). ``vs_baseline`` compares against a same-process JAX/Flax
reference implementation of the identical model/step, so the number is
hardware-independent (1.0 = parity with hand-written flax)."""

import json
import time

import numpy as np


def _bench_net(steps: int = 60, batch: int = 256, warmup: int = 5):
    import jax
    from __graft_entry__ import _lenet
    from deeplearning4j_tpu.data.dataset import DataSet

    net, _ = _lenet()
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (batch, 784)).astype("float32")
    y = np.eye(10, dtype="float32")[rng.integers(0, 10, batch)]
    ds = DataSet(x, y)

    step_fn = net._make_train_step()
    batch_t = net._batch_tuple(ds)
    params, state, opt = net.params, net.state, net.opt_state
    key = jax.random.PRNGKey(0)
    for i in range(warmup):
        params, state, opt, loss = step_fn(params, state, opt, batch_t,
                                           key, np.int32(i))
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for i in range(steps):
        params, state, opt, loss = step_fn(params, state, opt, batch_t,
                                           key, np.int32(i))
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    return steps * batch / dt


def _bench_flax_reference(steps: int = 60, batch: int = 256,
                          warmup: int = 5):
    """Same LeNet, hand-written in flax/optax — the perf reference."""
    import jax
    import jax.numpy as jnp
    import optax
    from flax import linen as nn

    class LeNet(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = x.reshape(x.shape[0], 28, 28, 1)
            x = nn.relu(nn.Conv(20, (5, 5), padding="VALID")(x))
            x = nn.max_pool(x, (2, 2), (2, 2))
            x = nn.relu(nn.Conv(50, (5, 5), padding="VALID")(x))
            x = nn.max_pool(x, (2, 2), (2, 2))
            x = x.reshape(x.shape[0], -1)
            x = nn.relu(nn.Dense(500)(x))
            return nn.Dense(10)(x)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(0, 1, (batch, 784)).astype("float32"))
    y = jnp.asarray(np.eye(10, dtype="float32")[
        rng.integers(0, 10, batch)])
    model = LeNet()
    params = model.init(jax.random.PRNGKey(0), x)
    tx = optax.adam(1e-3)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, x, y):
        def loss_fn(p):
            logits = model.apply(p, x)
            return optax.softmax_cross_entropy(logits, y).mean()
        loss, g = jax.value_and_grad(loss_fn)(params)
        u, opt2 = tx.update(g, opt, params)
        return optax.apply_updates(params, u), opt2, loss

    for _ in range(warmup):
        params, opt, loss = step(params, opt, x, y)
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt, loss = step(params, opt, x, y)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    return steps * batch / dt


def main():
    ours = _bench_net()
    ref = _bench_flax_reference()
    print(json.dumps({
        "metric": "LeNet-MNIST train throughput",
        "value": round(ours, 1),
        "unit": "images/sec",
        "vs_baseline": round(ours / ref, 3),
    }))


if __name__ == "__main__":
    main()
