"""Load generator for the serving fleet (and single servers).

The in-repo harness that turns "the router survives faults" into a
measured claim: drive ``/v1/predict`` (or ``/v1/generate``) at a
target rate or at fixed concurrency, record every latency in the
SAME histogram implementation the serving stack exposes
(``observability.registry.Histogram`` — percentiles come from the
metrics registry, not a side array), honor ``Retry-After`` backoff
on 429/503, and report exactly what the soak acceptance needs:
how many requests were sent, how many ever failed to get a
successful response (``failed`` — the "dropped requests" count),
and the latency distribution.

Two loop disciplines (the classic load-testing split):

- **closed loop** (``qps=None``): N workers fire back-to-back; the
  system's completion rate gates the arrival rate. Measures peak
  sustainable throughput, hides queueing delay.
- **open loop** (``qps=R``): arrivals are scheduled at R/s no matter
  how slow responses are (coordinated-omission-resistant); a bounded
  backlog models client impatience — overflow counts as
  ``not_sent`` rather than silently stretching the schedule.

Usage (library)::

    from tools.loadgen import LoadGen
    report = LoadGen(url, concurrency=16, total=2000).run()

CLI::

    python -m tools.loadgen --url http://127.0.0.1:8080 \
        --qps 200 --duration 30 --concurrency 32
"""

from __future__ import annotations

import argparse
import json
import queue
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, Optional

__all__ = ["LoadGen"]


def _default_body(i: int) -> dict:
    return {"model": "default", "inputs": [[0.0, 1.0, 2.0, 3.0]]}


class LoadGen:
    """Open/closed-loop HTTP load generator with registry-backed
    latency percentiles."""

    def __init__(self, url: str, route: str = "/v1/predict",
                 body_fn: Optional[Callable[[int], dict]] = None,
                 concurrency: int = 8,
                 qps: Optional[float] = None,
                 duration_s: Optional[float] = None,
                 total: Optional[int] = None,
                 timeout_s: float = 10.0,
                 max_retries: int = 2,
                 honor_retry_after: bool = True,
                 backlog_limit: Optional[int] = None,
                 registry=None):
        if duration_s is None and total is None:
            raise ValueError("give duration_s or total")
        from deeplearning4j_tpu.observability.registry import (
            MetricsRegistry)
        self.url = url.rstrip("/")
        self.route = route
        self.body_fn = body_fn or _default_body
        self.concurrency = max(1, concurrency)
        self.qps = qps
        self.duration_s = duration_s
        self.total = total
        self.timeout_s = timeout_s
        self.max_retries = max_retries
        self.honor_retry_after = honor_retry_after
        self.backlog_limit = (backlog_limit if backlog_limit
                              is not None else 8 * self.concurrency)
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.latency = self.registry.histogram(
            "loadgen_latency_seconds",
            help="client-observed request latency (seconds)",
            labels={"route": route})
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {
            "sent": 0, "ok": 0, "failed": 0, "retries": 0,
            "not_sent": 0, "retry_after_honored": 0}
        self._errors: Dict[str, int] = {}
        self._stop = threading.Event()

    # ---- one request, with backoff-aware retries ----
    def _once(self, i: int) -> None:
        body = json.dumps(self.body_fn(i)).encode()
        deadline = time.monotonic() + self.timeout_s
        attempts = 0
        with self._lock:
            # one REQUEST sent (retries are counted separately), so
            # sent == ok + failed holds and a drop rate computed
            # from sent vs ok is honest under failover
            self._counts["sent"] += 1
        t0 = time.perf_counter()
        while True:
            attempts += 1
            status, retry_after = self._fire(body, deadline)
            if status == 200:
                self.latency.record(time.perf_counter() - t0)
                with self._lock:
                    self._counts["ok"] += 1
                return
            retryable = status in ("neterr", 429, 503)
            with self._lock:
                if attempts <= self.max_retries and retryable:
                    self._counts["retries"] += 1
                else:
                    self._counts["failed"] += 1
                    key = str(status)
                    self._errors[key] = self._errors.get(key, 0) + 1
            if attempts > self.max_retries or not retryable:
                self.latency.record(time.perf_counter() - t0)
                return
            if retry_after and self.honor_retry_after:
                wait = min(retry_after,
                           max(0.0, deadline - time.monotonic()))
                if wait > 0:
                    with self._lock:
                        self._counts["retry_after_honored"] += 1
                    time.sleep(wait)
            if time.monotonic() >= deadline:
                with self._lock:
                    self._counts["failed"] += 1
                    self._errors["deadline"] = \
                        self._errors.get("deadline", 0) + 1
                self.latency.record(time.perf_counter() - t0)
                return

    def _fire(self, body: bytes, deadline: float):
        """(status | "neterr", retry_after_seconds or None)."""
        timeout = max(0.05, deadline - time.monotonic())
        req = urllib.request.Request(
            self.url + self.route, data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                r.read()
                return r.status, None
        except urllib.error.HTTPError as e:
            e.read()
            ra = e.headers.get("Retry-After")
            try:
                ra = float(ra) if ra is not None else None
            except ValueError:
                ra = None
            return e.code, ra
        except (urllib.error.URLError, OSError, TimeoutError):
            return "neterr", None

    # ---- loop disciplines ----
    def _closed_loop(self) -> None:
        seq = threading.Lock()
        counter = [0]
        t_end = (time.monotonic() + self.duration_s
                 if self.duration_s is not None else None)

        def worker():
            while not self._stop.is_set():
                with seq:
                    i = counter[0]
                    counter[0] += 1
                if self.total is not None and i >= self.total:
                    return
                if t_end is not None and time.monotonic() >= t_end:
                    return
                self._once(i)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self.concurrency)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def _open_loop(self) -> None:
        work: "queue.Queue" = queue.Queue(self.backlog_limit)

        def worker():
            while True:
                i = work.get()
                if i is None:
                    return
                self._once(i)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self.concurrency)]
        for t in threads:
            t.start()
        interval = 1.0 / float(self.qps)
        t_start = time.monotonic()
        t_end = (t_start + self.duration_s
                 if self.duration_s is not None else None)
        i = 0
        next_t = t_start
        while not self._stop.is_set():
            if self.total is not None and i >= self.total:
                break
            now = time.monotonic()
            if t_end is not None and now >= t_end:
                break
            if now < next_t:
                time.sleep(min(next_t - now, 0.05))
                continue
            # the OPEN-loop contract: this arrival happens NOW
            # whether or not the system kept up; a full backlog is a
            # client that gave up, not a schedule that stretched
            try:
                work.put_nowait(i)
            except queue.Full:
                with self._lock:
                    self._counts["not_sent"] += 1
            i += 1
            next_t += interval
        for _ in threads:
            work.put(None)
        for t in threads:
            t.join()

    # ---- entry ----
    def run(self) -> dict:
        t0 = time.monotonic()
        if self.qps is None:
            self._closed_loop()
        else:
            self._open_loop()
        wall = time.monotonic() - t0
        with self._lock:
            counts = dict(self._counts)
            errors = dict(self._errors)
        snap = self.latency.snapshot()
        report = {
            "route": self.route,
            "mode": "closed" if self.qps is None else "open",
            "target_qps": self.qps,
            "concurrency": self.concurrency,
            "wall_s": round(wall, 3),
            "achieved_qps": round(counts["ok"] / wall, 1)
            if wall > 0 else 0.0,
            "latency_ms": {
                "p50": round(self.latency.quantile(0.50) * 1e3, 3),
                "p95": round(self.latency.quantile(0.95) * 1e3, 3),
                "p99": round(self.latency.quantile(0.99) * 1e3, 3),
                "mean": round(snap["sum"] / snap["count"] * 1e3, 3)
                if snap["count"] else 0.0},
            "errors": errors,
        }
        report.update(counts)
        return report

    def stop(self) -> None:
        self._stop.set()


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="loadgen",
        description="open/closed-loop load generator for the "
                    "serving router / ModelServer")
    p.add_argument("--url", required=True,
                   help="base URL (router or replica)")
    p.add_argument("--route", default="/v1/predict")
    p.add_argument("--model", default="default")
    p.add_argument("--features", type=int, default=4,
                   help="input feature count for the default "
                        "predict body")
    p.add_argument("--concurrency", type=int, default=8)
    p.add_argument("--qps", type=float, default=None,
                   help="open-loop target rate; omit for closed "
                        "loop")
    p.add_argument("--duration", type=float, default=None,
                   help="seconds to run")
    p.add_argument("--total", type=int, default=None,
                   help="total requests (alternative to --duration)")
    p.add_argument("--timeout", type=float, default=10.0,
                   help="per-request budget incl. retries (seconds)")
    p.add_argument("--retries", type=int, default=2)
    args = p.parse_args(argv)
    if args.duration is None and args.total is None:
        args.duration = 10.0

    def body(i, model=args.model, feat=args.features):
        return {"model": model,
                "inputs": [[float((i + j) % 7) for j in range(feat)]]}

    gen = LoadGen(args.url, route=args.route, body_fn=body,
                  concurrency=args.concurrency, qps=args.qps,
                  duration_s=args.duration, total=args.total,
                  timeout_s=args.timeout, max_retries=args.retries)
    try:
        report = gen.run()
    except KeyboardInterrupt:
        gen.stop()
        report = {"interrupted": True}
    json.dump(report, sys.stdout, indent=2)
    sys.stdout.write("\n")
    return 0 if not report.get("failed") else 1


if __name__ == "__main__":
    sys.exit(main())
