"""Load generator for the serving fleet (and single servers).

The in-repo harness that turns "the router survives faults" into a
measured claim: drive ``/v1/predict`` (or ``/v1/generate``) at a
target rate or at fixed concurrency, record every latency in the
SAME histogram implementation the serving stack exposes
(``observability.registry.Histogram`` — percentiles come from the
metrics registry, not a side array), honor ``Retry-After`` backoff
on 429/503, and report exactly what the soak acceptance needs:
how many requests were sent, how many ever failed to get a
successful response (``failed`` — the "dropped requests" count),
and the latency distribution. Every error is also CLASSIFIED
(``error_classes`` in the report: ``connect_refused`` / ``reset`` /
``timeout`` / ``bad_body`` / ``5xx`` / ``4xx`` / ``shed_429_503`` /
``neterr``), retried or not — a network-chaos soak asserts WHICH
failure mode occurred, not just how many requests it cost.

Two loop disciplines (the classic load-testing split):

- **closed loop** (``qps=None``): N workers fire back-to-back; the
  system's completion rate gates the arrival rate. Measures peak
  sustainable throughput, hides queueing delay.
- **open loop** (``qps=R``): arrivals are scheduled at R/s no matter
  how slow responses are (coordinated-omission-resistant); a bounded
  backlog models client impatience — overflow counts as
  ``not_sent`` rather than silently stretching the schedule.

Streaming mode (``--mode generate``) drives ``/v1/generate`` with a
configurable **duplicate-prompt ratio**: that fraction of requests
reuses one shared prompt, the rest get unique prompts — the traffic
shape that makes prefix-cache wins measurable through the router.
After the run the report includes TTFT / inter-token percentiles
scraped from the server's own ``serving_ttft_seconds`` /
``serving_itl_seconds`` histograms (``--metrics-url``, defaulting to
the target), so the latency attribution comes from the serving
stack's instruments, not a client-side proxy.

Usage (library)::

    from tools.loadgen import LoadGen
    report = LoadGen(url, concurrency=16, total=2000).run()

Autoscaler-soak extensions: ``--profile step:LOW:HIGH:AT`` /
``ramp:LOW:HIGH`` schedule the open-loop QPS over the run (the
traffic spike the autoscaler must absorb), and ``--tier-mix
gold=0.2,standard=0.5,best_effort=0.3`` stamps each request with a
deterministic priority tier — the report then carries per-tier
latency and outcome percentiles (sent/ok/failed/shed per tier), the
evidence for "zero gold dropped, best-effort degraded first".

CLI::

    python -m tools.loadgen --url http://127.0.0.1:8080 \
        --qps 200 --duration 30 --concurrency 32
    python -m tools.loadgen --url http://127.0.0.1:8080 \
        --mode generate --dup-ratio 0.5 --total 200 --n-tokens 16
    python -m tools.loadgen --url http://127.0.0.1:8080 \
        --profile step:20:80:5 --duration 20 \
        --tier-mix gold=0.2,standard=0.5,best_effort=0.3
"""

from __future__ import annotations

import argparse
import http.client
import json
import queue
import socket
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, Optional

# the serving stack's tier literals, from their one authoritative
# home (a stdlib-only leaf module — loadgen already depends on the
# package for the registry histogram, so mirroring them here would
# only add drift risk)
from deeplearning4j_tpu.serving.tiers import TIERS as _TIERS

__all__ = ["LoadGen", "SearchWorkload", "generate_body_fn",
           "scrape_streaming_latency", "scrape_ttft_populations",
           "parse_profile", "parse_tier_mix", "tiered_body_fn"]


def _default_body(i: int) -> dict:
    return {"model": "default", "inputs": [[0.0, 1.0, 2.0, 3.0]]}


def parse_profile(spec):
    """Open-loop QPS schedule from a compact spec — the soak
    driver's traffic shape:

    - ``step:LOW:HIGH:AT`` (or ``...:AT:UNTIL``) — LOW q/s until
      ``AT`` seconds into the run, then HIGH (until ``UNTIL``, then
      back to LOW): the spike the autoscaler must absorb.
    - ``ramp:LOW:HIGH`` — linear from LOW to HIGH over the run.

    Returns ``qps_at(t_seconds, duration_s) -> float``; None for no
    profile (constant ``--qps``)."""
    if spec is None:
        return None
    parts = str(spec).split(":")
    kind = parts[0]
    try:
        nums = [float(x) for x in parts[1:]]
    except ValueError:
        raise ValueError(f"bad profile numbers in {spec!r}") from None
    if kind == "step":
        if len(nums) not in (3, 4):
            raise ValueError(
                f"step profile wants step:LOW:HIGH:AT[:UNTIL], got "
                f"{spec!r}")
        low, high, at = nums[:3]
        until = nums[3] if len(nums) == 4 else float("inf")

        def qps_at(t, duration_s=None):
            return high if at <= t < until else low
    elif kind == "ramp":
        if len(nums) != 2:
            raise ValueError(
                f"ramp profile wants ramp:LOW:HIGH, got {spec!r}")
        low, high = nums

        def qps_at(t, duration_s=None):
            if not duration_s:
                return high
            frac = min(1.0, max(0.0, t / duration_s))
            return low + (high - low) * frac
    else:
        raise ValueError(
            f"unknown profile kind {kind!r}; known: step, ramp")
    return qps_at


def parse_tier_mix(spec):
    """``gold=0.2,standard=0.5,best_effort=0.3`` -> dict (fractions
    normalised to sum 1). None/empty -> None (untiered traffic)."""
    if not spec:
        return None
    mix = {}
    for part in str(spec).split(","):
        name, _, frac = part.partition("=")
        name = name.strip().replace("-", "_")
        if name not in _TIERS:
            raise ValueError(
                f"unknown tier {name!r} in mix; known: {_TIERS}")
        mix[name] = float(frac)
    total = sum(mix.values())
    if total <= 0:
        raise ValueError(f"tier mix {spec!r} sums to zero")
    return {t: v / total for t, v in mix.items()}


def tiered_body_fn(base_fn, mix):
    """Wrap a body factory to stamp a deterministic per-ordinal
    ``tier`` drawn from ``mix`` (same spread idiom as the
    duplicate-prompt mix: replayable, no rng)."""
    tiers_sorted = [t for t in _TIERS if t in mix]
    edges = []
    acc = 0.0
    for t in tiers_sorted:
        acc += mix[t]
        edges.append((acc * 100.0, t))

    def body(i: int) -> dict:
        b = dict(base_fn(i))
        spread = (i * 37) % 100
        for edge, t in edges:
            if spread < edge:
                b["tier"] = t
                break
        else:
            b["tier"] = tiers_sorted[-1]
        return b

    return body


def generate_body_fn(model: str = "default", prompt_len: int = 16,
                     n_tokens: int = 16, vocab: int = 64,
                     dup_ratio: float = 0.0) -> Callable[[int], dict]:
    """Body factory for ``/v1/generate`` streaming load:
    deterministically, ``dup_ratio`` of requests (by ordinal) send
    ONE shared prompt — prefix-cache hits after the first completes
    — and the rest send unique prompts (cold prefill). Prompt ids
    stay in ``[1, vocab)``."""
    dup_per_100 = int(round(max(0.0, min(1.0, dup_ratio)) * 100))
    span = max(1, vocab - 1)
    shared = [1 + (7 * j) % span for j in range(prompt_len)]

    def body(i: int) -> dict:
        if (i * 37) % 100 < dup_per_100:     # deterministic spread
            prompt = shared
        else:
            prompt = [1 + (i + 3 * j) % span
                      for j in range(prompt_len)]
        return {"model": model, "prompt": prompt,
                "n_tokens": n_tokens}

    return body


class SearchWorkload:
    """``--mode search``: a Zipf-skewed query stream over a corpus
    plus the client-side recall@k oracle.

    A fixed pool of queries (corpus vectors + gaussian noise) is
    ranked by a seeded popularity permutation; request ordinal ``i``
    maps DETERMINISTICALLY to a pool rank through the Zipf CDF (same
    replayable-spread idiom as the duplicate-prompt mix), so head
    queries repeat the way real retrieval traffic does — the shape
    that makes batching and cache effects measurable. The exact
    brute-force top-k over the corpus is computed host-side up
    front; every 200 response's ids score against it, and the report
    carries the measured ``recall_at_k``.
    """

    def __init__(self, vectors, ids=None, k: int = 10,
                 nprobe: Optional[int] = None,
                 metric: str = "cosine", pool: int = 256,
                 zipf_s: float = 1.1, noise: float = 0.05,
                 seed: int = 0):
        import numpy as np
        self._np = np
        vectors = np.asarray(vectors, np.float32)
        self._ids = (np.arange(vectors.shape[0]) if ids is None
                     else np.asarray(ids))
        self.k = int(k)
        self.nprobe = nprobe
        rng = np.random.default_rng(seed)
        pool = min(int(pool), vectors.shape[0])
        picks = rng.choice(vectors.shape[0], size=pool,
                           replace=False)
        self.queries = (vectors[picks]
                        + noise * rng.standard_normal(
                            (pool, vectors.shape[1]))
                        ).astype(np.float32)
        # Zipf CDF over pool ranks: rank r has mass 1/(r+1)^s
        w = 1.0 / np.power(np.arange(1, pool + 1, dtype=np.float64),
                           float(zipf_s))
        self._cdf = np.cumsum(w) / np.sum(w)
        self._oracle = self._exact_topk(vectors, metric)

    def _exact_topk(self, corpus, metric):
        np = self._np
        q = self.queries.astype(np.float64)
        m = corpus.astype(np.float64)
        if metric == "cosine":
            qn = q / np.maximum(
                np.linalg.norm(q, axis=1, keepdims=True), 1e-12)
            mn = m / np.maximum(
                np.linalg.norm(m, axis=1, keepdims=True), 1e-12)
            scores = qn @ mn.T
        elif metric == "dot":
            scores = q @ m.T
        else:                                   # euclidean
            scores = (2.0 * (q @ m.T)
                      - np.sum(m * m, axis=1)[None, :]
                      - np.sum(q * q, axis=1)[:, None])
        order = np.argsort(-scores, axis=1, kind="stable")
        return [set(int(self._ids[p]) for p in row[:self.k])
                for row in order]

    def rank_of(self, i: int) -> int:
        """ordinal -> Zipf-drawn pool rank, replayable (golden-ratio
        low-discrepancy spread through the CDF, no rng at request
        time)."""
        u = ((i * 2654435761) % (1 << 32)) / float(1 << 32)
        return int(self._np.searchsorted(self._cdf, u,
                                         side="right"))

    def body(self, i: int) -> dict:
        r = min(self.rank_of(i), len(self.queries) - 1)
        b = {"vector": [float(x) for x in self.queries[r]],
             "k": self.k}
        if self.nprobe is not None:
            b["nprobe"] = int(self.nprobe)
        return b

    def make_response_cb(self, lock: threading.Lock,
                         acc: Dict[str, float]):
        """Recall accumulator fed by LoadGen's response hook: the
        ordinal recomputes its pool rank deterministically, so no
        state rides in the request."""
        def cb(i: int, data: bytes) -> None:
            r = min(self.rank_of(i), len(self.queries) - 1)
            got = json.loads(data.decode())
            ids = {int(e["id"]) for e in got["results"][0]}
            hits = len(ids & self._oracle[r])
            with lock:
                acc["hits"] = acc.get("hits", 0.0) + hits
                acc["total"] = acc.get("total", 0.0) + self.k
        return cb

    def recall(self, acc: Dict[str, float]) -> Optional[float]:
        if not acc.get("total"):
            return None
        return round(acc["hits"] / acc["total"], 4)


def _histogram_quantiles(buckets: Dict[float, float], count: float):
    """p50/p95/p99 from cumulative Prometheus buckets (upper-edge
    estimate, matching how coarse scrape-side quantiles are always
    read)."""
    out = {}
    edges = sorted(buckets)
    finite = [e for e in edges if e != float("inf")]
    for name, q in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99)):
        target = q * count
        val = None
        for e in edges:
            if buckets[e] >= target:
                val = e
                break
        if val is None or val == float("inf"):
            # an observation above every finite bucket: report the
            # highest finite edge (the standard scrape-side clamp)
            val = finite[-1] if finite else 0.0
        out[name] = round(val * 1e3, 3)
    return out


def _label_value(line: str, label: str) -> Optional[str]:
    marker = label + '="'
    at = line.find(marker)
    if at < 0:
        return None
    return line[at + len(marker):line.index('"', at + len(marker))]


def _accumulate_histogram(text: str, metric: str,
                          buckets: Dict[float, float],
                          counts: Dict[str, float],
                          pop_buckets: Dict[str, Dict[float, float]],
                          pop_counts: Dict[str, float]) -> None:
    """Fold one Prometheus exposition's ``metric`` histogram lines
    into running bucket/count accumulators (overall + split by the
    ``population`` label) — the ONE parser behind both the per-
    server scrape below and bench.py's fleet-merged TTFT read
    (summing buckets before quantiles; merging per-server quantiles
    would be statistically wrong)."""
    for line in text.splitlines():
        if not line.startswith(metric):
            continue
        rest = line[len(metric):]
        try:
            value = float(line.rsplit(" ", 1)[1])
        except (IndexError, ValueError):
            continue
        pop = _label_value(line, "population")
        if rest.startswith("_bucket"):
            le = _label_value(line, "le")
            if le is None:
                continue
            edge = float("inf") if le in ("+Inf", "inf") \
                else float(le)
            buckets[edge] = buckets.get(edge, 0.0) + value
            if pop is not None:
                pb = pop_buckets.setdefault(pop, {})
                pb[edge] = pb.get(edge, 0.0) + value
        elif rest.startswith("_count"):
            counts["total"] = counts.get("total", 0.0) + value
            if pop is not None:
                pop_counts[pop] = pop_counts.get(pop, 0.0) + value


def _quantile_entry(buckets: Dict[float, float],
                    count: float) -> dict:
    entry = {"count": int(count)}
    entry.update(_histogram_quantiles(buckets, count)
                 if count else {"p50": 0.0, "p95": 0.0,
                                "p99": 0.0})
    return entry


def _fetch_exposition(url: str, timeout_s: float) -> str:
    req = urllib.request.Request(
        url.rstrip("/") + "/metrics?format=prometheus")
    with urllib.request.urlopen(req, timeout=timeout_s) as r:
        return r.read().decode()


def scrape_streaming_latency(url: str,
                             timeout_s: float = 5.0) -> dict:
    """TTFT / inter-token latency percentiles from a server's OWN
    metrics: parses the Prometheus exposition's
    ``serving_ttft_seconds`` / ``serving_itl_seconds`` histograms
    (buckets summed across model versions). Returns
    ``{metric: {count, p50, p95, p99}}`` in milliseconds; TTFT is
    ADDITIONALLY split by the ``population`` label into ``cold``
    vs ``prefix_hit`` sub-entries — the headline ratio of prefix
    caching / KV-aware routing, measurable without
    post-processing."""
    text = _fetch_exposition(url, timeout_s)
    out = {}
    for metric in ("serving_ttft_seconds", "serving_itl_seconds"):
        buckets: Dict[float, float] = {}
        counts: Dict[str, float] = {}
        pop_buckets: Dict[str, Dict[float, float]] = {}
        pop_counts: Dict[str, float] = {}
        _accumulate_histogram(text, metric, buckets, counts,
                              pop_buckets, pop_counts)
        entry = _quantile_entry(buckets, counts.get("total", 0.0))
        for pop, pc in pop_counts.items():
            entry[pop] = _quantile_entry(pop_buckets[pop], pc)
        out[metric] = entry
    return out


def scrape_version_breakdown(url: str,
                             timeout_s: float = 5.0) -> dict:
    """Per-MODEL-VERSION outcome split from the router's own
    per-version accounting (``router_version_requests_total`` /
    ``router_version_errors_total`` /
    ``router_version_latency_seconds``, all labeled ``version``):
    ``{version: {ok, failed, p99_ms}}`` — during a canary rollout
    this is the client-side read of how each version actually
    behaved, split exactly the way the promotion gate saw it.
    Returns ``{}`` against a target without version series (a bare
    ModelServer)."""
    text = _fetch_exposition(url, timeout_s)
    req: Dict[str, float] = {}
    err: Dict[str, float] = {}
    buckets: Dict[str, Dict[float, float]] = {}
    counts: Dict[str, float] = {}
    for line in text.splitlines():
        if not line.startswith("router_version_"):
            continue
        ver = _label_value(line, "version")
        if ver is None:
            continue
        try:
            value = float(line.rsplit(" ", 1)[1])
        except (IndexError, ValueError):
            continue
        if line.startswith("router_version_requests_total"):
            req[ver] = req.get(ver, 0.0) + value
        elif line.startswith("router_version_errors_total"):
            err[ver] = err.get(ver, 0.0) + value
        elif line.startswith(
                "router_version_latency_seconds_bucket"):
            le = _label_value(line, "le")
            if le is None:
                continue
            edge = float("inf") if le in ("+Inf", "inf") \
                else float(le)
            vb = buckets.setdefault(ver, {})
            vb[edge] = vb.get(edge, 0.0) + value
        elif line.startswith(
                "router_version_latency_seconds_count"):
            counts[ver] = counts.get(ver, 0.0) + value
    out = {}
    for ver in sorted(req, key=lambda v: (len(v), v)):
        failed = int(err.get(ver, 0.0))
        entry = {"ok": int(req[ver]) - failed, "failed": failed}
        n = counts.get(ver, 0.0)
        entry["p99_ms"] = _histogram_quantiles(
            buckets.get(ver, {}), n)["p99"] if n else 0.0
        out[ver] = entry
    return out


def scrape_ttft_populations(urls, timeout_s: float = 5.0) -> dict:
    """Fleet-merged TTFT split: sum every server's
    ``serving_ttft_seconds`` buckets per ``population`` label, then
    take quantiles — ``{"cold": {count, p50, p95, p99},
    "prefix_hit": {...}}`` in milliseconds."""
    buckets: Dict[float, float] = {}
    counts: Dict[str, float] = {}
    pop_buckets: Dict[str, Dict[float, float]] = {
        "cold": {}, "prefix_hit": {}}
    pop_counts: Dict[str, float] = {"cold": 0.0, "prefix_hit": 0.0}
    for url in urls:
        _accumulate_histogram(_fetch_exposition(url, timeout_s),
                              "serving_ttft_seconds", buckets,
                              counts, pop_buckets, pop_counts)
    return {pop: _quantile_entry(pop_buckets[pop], pop_counts[pop])
            for pop in ("cold", "prefix_hit")}


class LoadGen:
    """Open/closed-loop HTTP load generator with registry-backed
    latency percentiles."""

    def __init__(self, url: str, route: str = "/v1/predict",
                 body_fn: Optional[Callable[[int], dict]] = None,
                 concurrency: int = 8,
                 qps: Optional[float] = None,
                 duration_s: Optional[float] = None,
                 total: Optional[int] = None,
                 timeout_s: float = 10.0,
                 max_retries: int = 2,
                 honor_retry_after: bool = True,
                 backlog_limit: Optional[int] = None,
                 profile: Optional[Callable] = None,
                 registry=None,
                 response_cb: Optional[Callable[[int, bytes],
                                               None]] = None):
        if duration_s is None and total is None:
            raise ValueError("give duration_s or total")
        from deeplearning4j_tpu.observability.registry import (
            MetricsRegistry)
        self.url = url.rstrip("/")
        self.route = route
        self.body_fn = body_fn or _default_body
        self.concurrency = max(1, concurrency)
        self.qps = qps
        self.profile = profile
        self.duration_s = duration_s
        self.total = total
        self.timeout_s = timeout_s
        self.max_retries = max_retries
        self.honor_retry_after = honor_retry_after
        self.backlog_limit = (backlog_limit if backlog_limit
                              is not None else 8 * self.concurrency)
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        # optional per-success body hook — the search mode's recall
        # accounting reads the returned neighbor ids through it
        self.response_cb = response_cb
        self.latency = self.registry.histogram(
            "loadgen_latency_seconds",
            help="client-observed request latency (seconds)",
            labels={"route": route})
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {
            "sent": 0, "ok": 0, "failed": 0, "retries": 0,
            "not_sent": 0, "retry_after_honored": 0}
        self._errors: Dict[str, int] = {}
        self._error_classes: Dict[str, int] = {}
        # per-tier outcome + latency accounting (created lazily on
        # the first tiered body; untiered runs pay nothing)
        self._tier_counts: Dict[str, Dict[str, int]] = {}
        self._tier_errors: Dict[str, Dict[str, int]] = {}
        self._tier_latency: Dict[str, object] = {}
        self._stop = threading.Event()

    def _tier_state(self, tier: str):
        with self._lock:
            if tier not in self._tier_counts:
                self._tier_counts[tier] = {
                    "sent": 0, "ok": 0, "failed": 0, "retries": 0,
                    "shed": 0}
                self._tier_errors[tier] = {}
                self._tier_latency[tier] = self.registry.histogram(
                    "loadgen_latency_seconds",
                    help="client-observed request latency (seconds)",
                    labels={"route": self.route, "tier": tier})
            return (self._tier_counts[tier], self._tier_errors[tier],
                    self._tier_latency[tier])

    # ---- one request, with backoff-aware retries ----
    def _once(self, i: int) -> None:
        body_obj = self.body_fn(i)
        tier = body_obj.get("tier")
        tc = te = th = None
        if tier is not None:
            tc, te, th = self._tier_state(str(tier))
        body = json.dumps(body_obj).encode()
        deadline = time.monotonic() + self.timeout_s
        attempts = 0
        with self._lock:
            # one REQUEST sent (retries are counted separately), so
            # sent == ok + failed holds and a drop rate computed
            # from sent vs ok is honest under failover
            self._counts["sent"] += 1
            if tc is not None:
                tc["sent"] += 1
        t0 = time.perf_counter()

        def record():
            # the ONE terminal latency record (success, retries
            # exhausted, deadline): whole-request wall time into the
            # route histogram and, when tiered, the tier's
            dt = time.perf_counter() - t0
            self.latency.record(dt)
            if th is not None:
                th.record(dt)

        while True:
            attempts += 1
            status, retry_after, data, klass = self._fire(body,
                                                          deadline)
            if klass is not None:
                with self._lock:
                    # every error OCCURRENCE by class, retried or
                    # not: a zero-drop soak still asserts which
                    # failure mode its retries absorbed
                    self._error_classes[klass] = \
                        self._error_classes.get(klass, 0) + 1
            if status in (429, 503) and tc is not None:
                with self._lock:
                    # every shed response the tier absorbed, retried
                    # or not — the "best-effort degraded first"
                    # evidence
                    tc["shed"] += 1
            if status == 200:
                record()
                with self._lock:
                    self._counts["ok"] += 1
                    if tc is not None:
                        tc["ok"] += 1
                if self.response_cb is not None:
                    try:
                        self.response_cb(i, data)
                    except Exception:
                        pass        # accounting hook, never fatal
                return
            retryable = status in ("neterr", 429, 503)
            with self._lock:
                if attempts <= self.max_retries and retryable:
                    self._counts["retries"] += 1
                    if tc is not None:
                        tc["retries"] += 1
                else:
                    self._counts["failed"] += 1
                    # terminal network failures keep their CLASS as
                    # the key ("timeout", "reset", ...), not an
                    # opaque "neterr"
                    key = klass if status == "neterr" \
                        else str(status)
                    self._errors[key] = self._errors.get(key, 0) + 1
                    if tc is not None:
                        tc["failed"] += 1
                        te[key] = te.get(key, 0) + 1
            if attempts > self.max_retries or not retryable:
                record()
                return
            if retry_after and self.honor_retry_after:
                wait = min(retry_after,
                           max(0.0, deadline - time.monotonic()))
                if wait > 0:
                    with self._lock:
                        self._counts["retry_after_honored"] += 1
                    time.sleep(wait)
            if time.monotonic() >= deadline:
                with self._lock:
                    self._counts["failed"] += 1
                    self._errors["deadline"] = \
                        self._errors.get("deadline", 0) + 1
                    if tc is not None:
                        tc["failed"] += 1
                        te["deadline"] = te.get("deadline", 0) + 1
                record()
                return

    @staticmethod
    def _classify(e: BaseException) -> str:
        """The error-class taxonomy a chaos soak asserts against.
        Unwraps urllib's URLError so a refused connect classifies
        the same whether the OS error arrived bare or wrapped."""
        if isinstance(e, urllib.error.URLError) \
                and isinstance(e.reason, BaseException):
            e = e.reason
        if isinstance(e, ConnectionRefusedError):
            return "connect_refused"
        if isinstance(e, (ConnectionResetError, BrokenPipeError,
                          http.client.RemoteDisconnected)):
            return "reset"
        if isinstance(e, (TimeoutError, socket.timeout)):
            return "timeout"
        if isinstance(e, http.client.IncompleteRead):
            return "bad_body"
        if isinstance(e, http.client.HTTPException):
            # BadStatusLine & co: the response bytes were mangled
            # mid-stream (a reset or corruption inside the status
            # line) — the body never parsed as HTTP at all
            return "bad_body"
        return "neterr"

    def _fire(self, body: bytes, deadline: float):
        """(status | "neterr", retry_after_seconds or None, body,
        error class or None). A 2xx whose body is not the JSON the
        server framed (truncated / corrupted on the wire) is a
        ``bad_body`` network error, never a success — and never a
        raw exception unwinding a worker thread."""
        timeout = max(0.05, deadline - time.monotonic())
        req = urllib.request.Request(
            self.url + self.route, data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                status, data = r.status, r.read()
        except urllib.error.HTTPError as e:
            e.read()
            ra = e.headers.get("Retry-After")
            try:
                ra = float(ra) if ra is not None else None
            except ValueError:
                ra = None
            klass = ("shed_429_503" if e.code in (429, 503)
                     else "5xx" if e.code >= 500 else "4xx")
            return e.code, ra, None, klass
        except (urllib.error.URLError, OSError, TimeoutError,
                http.client.HTTPException) as e:
            return "neterr", None, None, self._classify(e)
        try:
            json.loads(data.decode())
        except (ValueError, UnicodeDecodeError):
            return "neterr", None, None, "bad_body"
        return status, None, data, None

    # ---- loop disciplines ----
    def _closed_loop(self) -> None:
        seq = threading.Lock()
        counter = [0]
        t_end = (time.monotonic() + self.duration_s
                 if self.duration_s is not None else None)

        def worker():
            while not self._stop.is_set():
                with seq:
                    i = counter[0]
                    counter[0] += 1
                if self.total is not None and i >= self.total:
                    return
                if t_end is not None and time.monotonic() >= t_end:
                    return
                self._once(i)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self.concurrency)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    def _open_loop(self) -> None:
        work: "queue.Queue" = queue.Queue(self.backlog_limit)

        def worker():
            while True:
                try:
                    # heartbeat get (GL008): a wedged arrival loop
                    # must not strand workers in a blocking get
                    # forever — they re-check the stop flag instead
                    i = work.get(timeout=0.5)
                except queue.Empty:
                    if self._stop.is_set():
                        return
                    continue
                if i is None:
                    return
                self._once(i)

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self.concurrency)]
        for t in threads:
            t.start()
        interval = (1.0 / float(self.qps)
                    if self.profile is None else None)
        t_start = time.monotonic()
        t_end = (t_start + self.duration_s
                 if self.duration_s is not None else None)
        i = 0
        next_t = t_start
        while not self._stop.is_set():
            if self.total is not None and i >= self.total:
                break
            now = time.monotonic()
            if t_end is not None and now >= t_end:
                break
            if self.profile is not None:
                # time-varying schedule (step / ramp): re-read the
                # target rate every pass so a QPS step lands at its
                # scheduled second, not an arrival later
                rate = float(self.profile(now - t_start,
                                          self.duration_s))
                if rate <= 0:
                    # a zero-rate phase owes no arrivals: idle, and
                    # re-anchor the schedule so the next nonzero
                    # phase starts from NOW instead of replaying a
                    # backlog of arrivals the schedule never asked
                    # for
                    next_t = now + 0.05
                    time.sleep(0.05)
                    continue
                interval = 1.0 / rate
            if now < next_t:
                time.sleep(min(next_t - now, 0.05))
                continue
            # the OPEN-loop contract: this arrival happens NOW
            # whether or not the system kept up; a full backlog is a
            # client that gave up, not a schedule that stretched
            try:
                work.put_nowait(i)
            except queue.Full:
                with self._lock:
                    self._counts["not_sent"] += 1
            i += 1
            next_t += interval
        for _ in threads:
            work.put(None)
        for t in threads:
            t.join()

    # ---- entry ----
    def run(self) -> dict:
        t0 = time.monotonic()
        if self.qps is None and self.profile is None:
            self._closed_loop()
        else:
            self._open_loop()
        wall = time.monotonic() - t0
        with self._lock:
            counts = dict(self._counts)
            errors = dict(self._errors)
            error_classes = dict(self._error_classes)
        snap = self.latency.snapshot()
        report = {
            "route": self.route,
            "mode": ("closed" if self.qps is None
                     and self.profile is None else "open"),
            "target_qps": self.qps,
            "concurrency": self.concurrency,
            "wall_s": round(wall, 3),
            "achieved_qps": round(counts["ok"] / wall, 1)
            if wall > 0 else 0.0,
            "latency_ms": {
                "p50": round(self.latency.quantile(0.50) * 1e3, 3),
                "p95": round(self.latency.quantile(0.95) * 1e3, 3),
                "p99": round(self.latency.quantile(0.99) * 1e3, 3),
                "mean": round(snap["sum"] / snap["count"] * 1e3, 3)
                if snap["count"] else 0.0},
            "errors": errors,
            "error_classes": error_classes,
        }
        report.update(counts)
        with self._lock:
            tier_counts = {t: dict(c)
                           for t, c in self._tier_counts.items()}
            tier_errors = {t: dict(e)
                           for t, e in self._tier_errors.items()}
            tier_hists = dict(self._tier_latency)
        if tier_counts:
            tiers_rep = {}
            for t, c in tier_counts.items():
                h = tier_hists[t]
                entry = dict(c)
                entry["errors"] = tier_errors.get(t, {})
                entry["latency_ms"] = {
                    "p50": round(h.quantile(0.50) * 1e3, 3),
                    "p95": round(h.quantile(0.95) * 1e3, 3),
                    "p99": round(h.quantile(0.99) * 1e3, 3)}
                tiers_rep[t] = entry
            report["tiers"] = tiers_rep
        return report

    def stop(self) -> None:
        self._stop.set()


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="loadgen",
        description="open/closed-loop load generator for the "
                    "serving router / ModelServer")
    p.add_argument("--url", required=True,
                   help="base URL (router or replica)")
    p.add_argument("--route", default=None,
                   help="override the request path (default: by "
                        "--mode)")
    p.add_argument("--mode", choices=("predict", "generate",
                                      "search"),
                   default="predict",
                   help="predict = one-shot /v1/predict bodies; "
                        "generate = streaming /v1/generate bodies "
                        "with a duplicate-prompt mix; search = "
                        "Zipf-skewed /v1/search queries over "
                        "--corpus with a client-side recall@k "
                        "oracle")
    p.add_argument("--model", default="default")
    p.add_argument("--features", type=int, default=4,
                   help="input feature count for the default "
                        "predict body")
    p.add_argument("--prompt-len", type=int, default=16,
                   help="generate mode: prompt tokens per request")
    p.add_argument("--n-tokens", type=int, default=16,
                   help="generate mode: tokens to decode per request")
    p.add_argument("--vocab", type=int, default=64,
                   help="generate mode: prompt ids drawn from "
                        "[1, vocab)")
    p.add_argument("--dup-ratio", type=float, default=0.0,
                   help="generate mode: fraction of requests reusing "
                        "ONE shared prompt (prefix-cache hits after "
                        "the first completes)")
    p.add_argument("--metrics-url", default=None,
                   help="generate mode: scrape TTFT/ITL histogram "
                        "percentiles from this server after the run "
                        "(default: --url; 'off' disables)")
    p.add_argument("--corpus", default=None, metavar="SPEC",
                   help="search mode: the corpus the TARGET serves "
                        "('random:n=..,dim=..,seed=..' or .npz) — "
                        "must match the server's --index so the "
                        "recall oracle is exact")
    p.add_argument("--k", type=int, default=10,
                   help="search mode: neighbors per query")
    p.add_argument("--nprobe", type=int, default=None,
                   help="search mode: IVF cells probed (omit for "
                        "the server default)")
    p.add_argument("--metric", default="cosine",
                   choices=("cosine", "dot", "euclidean"),
                   help="search mode: oracle metric (match the "
                        "server's --index-metric)")
    p.add_argument("--zipf-s", type=float, default=1.1,
                   help="search mode: Zipf skew exponent of the "
                        "query popularity distribution")
    p.add_argument("--query-pool", type=int, default=256,
                   help="search mode: distinct query count")
    p.add_argument("--query-noise", type=float, default=0.05,
                   help="search mode: gaussian noise stddev added "
                        "to each pooled corpus vector")
    p.add_argument("--seed", type=int, default=0,
                   help="search mode: query pool seed")
    p.add_argument("--concurrency", type=int, default=8)
    p.add_argument("--qps", type=float, default=None,
                   help="open-loop target rate; omit for closed "
                        "loop")
    p.add_argument("--profile", default=None, metavar="SPEC",
                   help="open-loop QPS schedule: 'step:LOW:HIGH:AT"
                        "[:UNTIL]' (LOW q/s, stepping to HIGH at AT "
                        "seconds) or 'ramp:LOW:HIGH' (linear over "
                        "the run) — the autoscaler soak's traffic "
                        "shape; overrides --qps")
    p.add_argument("--tier-mix", default=None, metavar="MIX",
                   help="per-tier request mix, e.g. "
                        "'gold=0.2,standard=0.5,best_effort=0.3': "
                        "each request carries a deterministically "
                        "assigned tier and the report adds per-tier "
                        "latency/outcome percentiles")
    p.add_argument("--duration", type=float, default=None,
                   help="seconds to run")
    p.add_argument("--total", type=int, default=None,
                   help="total requests (alternative to --duration)")
    p.add_argument("--timeout", type=float, default=10.0,
                   help="per-request budget incl. retries (seconds)")
    p.add_argument("--retries", type=int, default=2)
    p.add_argument("--out", default=None, metavar="PATH",
                   help="also write the full report as JSON to PATH "
                        "(machine-readable: bench legs and the fleet "
                        "collector tests read this instead of "
                        "parsing stdout)")
    args = p.parse_args(argv)
    if args.duration is None and args.total is None:
        args.duration = 10.0

    workload = None
    recall_lock = threading.Lock()
    recall_acc: Dict[str, float] = {}
    if args.mode == "generate":
        route = args.route or "/v1/generate"
        body = generate_body_fn(model=args.model,
                                prompt_len=args.prompt_len,
                                n_tokens=args.n_tokens,
                                vocab=args.vocab,
                                dup_ratio=args.dup_ratio)
    elif args.mode == "search":
        if not args.corpus:
            p.error("--mode search needs --corpus (the same spec "
                    "the server's --index loaded)")
        from deeplearning4j_tpu.cli import _load_corpus
        try:
            ids, vectors, _, _ = _load_corpus(args.corpus)
        except SystemExit as e:
            p.error(str(e))
        route = args.route or "/v1/search"
        workload = SearchWorkload(
            vectors, ids=ids, k=args.k, nprobe=args.nprobe,
            metric=args.metric, pool=args.query_pool,
            zipf_s=args.zipf_s, noise=args.query_noise,
            seed=args.seed)
        body = workload.body
    else:
        route = args.route or "/v1/predict"

        def body(i, model=args.model, feat=args.features):
            return {"model": model,
                    "inputs": [[float((i + j) % 7)
                                for j in range(feat)]]}

    try:
        mix = parse_tier_mix(args.tier_mix)
        profile = parse_profile(args.profile)
    except ValueError as e:
        p.error(str(e))
    if mix is not None:
        body = tiered_body_fn(body, mix)
    if profile is not None and args.duration is None:
        p.error("--profile needs --duration (the schedule is "
                "expressed in run seconds)")
    gen = LoadGen(args.url, route=route, body_fn=body,
                  concurrency=args.concurrency, qps=args.qps,
                  profile=profile,
                  duration_s=args.duration, total=args.total,
                  timeout_s=args.timeout, max_retries=args.retries,
                  response_cb=workload.make_response_cb(
                      recall_lock, recall_acc)
                  if workload is not None else None)
    try:
        report = gen.run()
    except KeyboardInterrupt:
        gen.stop()
        report = {"interrupted": True}
    if workload is not None:
        with recall_lock:
            report["search"] = {
                "recall_at_k": workload.recall(recall_acc),
                "k": args.k, "nprobe": args.nprobe,
                "metric": args.metric, "zipf_s": args.zipf_s,
                "query_pool": len(workload.queries),
                "scored": int(recall_acc.get("total", 0)
                              // max(args.k, 1))}
    if args.mode == "generate" and args.metrics_url != "off":
        # the serving stack's OWN streaming histograms: TTFT / ITL
        # percentiles as the server measured them, not a client proxy
        try:
            report["streaming"] = scrape_streaming_latency(
                args.metrics_url or args.url)
            report["dup_ratio"] = args.dup_ratio
        except Exception as e:        # scrape is best-effort
            report["streaming_error"] = str(e)
    if args.metrics_url != "off":
        # per-model-version outcome split (router targets only):
        # during a rollout the report shows ok/failed/p99 for the
        # incumbent AND the candidate separately
        try:
            versions = scrape_version_breakdown(
                args.metrics_url or args.url)
            if versions:
                report["versions"] = versions
        except Exception:
            pass          # not a router, or no metrics: no split
    json.dump(report, sys.stdout, indent=2)
    sys.stdout.write("\n")
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
    return 0 if not report.get("failed") else 1


if __name__ == "__main__":
    sys.exit(main())
