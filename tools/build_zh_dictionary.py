"""Build the bundled Chinese lattice dictionary from jieba's dict.txt.

jieba (https://github.com/fxsjy/jieba, MIT license) ships a 349k-entry
frequency dictionary `word count tag`. We derive the framework's
bundled core: the top-N entries by count, plus EVERY single-character
entry (single chars keep the lattice connected when a compound is
missing), re-written in the framework's dictionary TSV format
(see deeplearning4j_tpu/nlp/lattice.py docstring).

Reproducible: `python tools/build_zh_dictionary.py` regenerates
deeplearning4j_tpu/nlp/data/zh_core.tsv.gz byte-for-byte given the
same jieba version (0.42.1 in this image).
"""

import gzip
import os

TOP_N = 60_000

HEADER = """\
# Chinese core dictionary for the lattice segmenter.
# Derived from jieba 0.42.1 dict.txt (MIT license,
# https://github.com/fxsjy/jieba): top {n} entries by corpus count
# plus all single-character entries. Format: word<TAB>count<TAB>tag.
# Regenerate with: python tools/build_zh_dictionary.py
"""


def main():
    import jieba
    src = os.path.join(os.path.dirname(jieba.__file__), "dict.txt")
    entries = []
    with open(src, encoding="utf-8") as f:
        for line in f:
            parts = line.split()
            if len(parts) >= 2:
                word, count = parts[0], int(parts[1])
                tag = parts[2] if len(parts) > 2 else "*"
                entries.append((word, count, tag))
    entries.sort(key=lambda e: -e[1])
    keep = entries[:TOP_N] + [e for e in entries[TOP_N:]
                              if len(e[0]) == 1]
    keep.sort(key=lambda e: (-e[1], e[0]))     # deterministic output
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "deeplearning4j_tpu", "nlp",
        "data", "zh_core.tsv.gz")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    import io
    buf = io.StringIO()
    buf.write(HEADER.format(n=TOP_N))
    for word, count, tag in keep:
        buf.write(f"{word}\t{count}\t{tag}\n")
    with open(out, "wb") as raw:
        # mtime=0 → byte-reproducible output across rebuilds
        with gzip.GzipFile(fileobj=raw, mode="wb", compresslevel=9,
                           mtime=0) as f:
            f.write(buf.getvalue().encode("utf-8"))
    print(f"{out}: {len(keep)} entries, "
          f"{os.path.getsize(out) / 1e6:.2f} MB")


if __name__ == "__main__":
    main()
