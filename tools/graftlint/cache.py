"""On-disk result cache for the per-file (file-scope) lint pass.

Keyed by content: a file's cache key is the sha256 of its
repo-relative path plus its bytes, so any edit — or a rename —
invalidates exactly that file. The whole cache is additionally
guarded by a **toolchain fingerprint** (sha256 over the sources of
``tools/graftlint`` itself): editing any rule, the engine, or this
module discards every entry, so a rule fix can never be masked by
stale results.

Only file-scope rule findings are cached. Repo-scope rules (the
lock graph, the call-graph passes, doc lints) are cross-file by
nature and always re-run — they are also the reason a warm cache
still parses: the cache removes rule *execution* per unchanged
file, which is where the time goes as the rule set grows.

The store is one JSON file (default ``.graftlint_cache.json`` at
the repo root, written atomically via rename); delete it at will.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Dict, List, Optional, Sequence

from tools.graftlint.core import Finding

_CACHE_VERSION = 1


def toolchain_fingerprint() -> str:
    """sha256 over the graftlint sources themselves."""
    root = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in sorted(os.walk(root)):
        dirnames[:] = sorted(d for d in dirnames
                             if d != "__pycache__")
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            full = os.path.join(dirpath, fname)
            h.update(os.path.relpath(full, root).encode())
            with open(full, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


def file_key(relpath: str, source: str) -> str:
    h = hashlib.sha256()
    h.update(relpath.encode())
    h.update(b"\0")
    h.update(source.encode("utf-8", "replace"))
    return h.hexdigest()


class LintCache:
    # superseded file versions leave dead entries behind (a new
    # content hash per edit); cap the store and evict least-recently
    # used at save so the JSON file stays bounded over long histories
    MAX_ENTRIES = 8192

    def __init__(self, path: str):
        self.path = path
        self.fingerprint = toolchain_fingerprint()
        self._entries: Dict[str, dict] = {}
        self._dirty = False
        self._clock = 0
        self.hits = 0
        self.misses = 0
        self._load()
        self._clock = max((e.get("t", 0)
                           for e in self._entries.values()),
                          default=0)

    def _load(self) -> None:
        try:
            with open(self.path, encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, ValueError):
            return
        if data.get("version") != _CACHE_VERSION or \
                data.get("fingerprint") != self.fingerprint:
            return          # toolchain changed: start empty
        entries = data.get("entries")
        if isinstance(entries, dict):
            self._entries = entries

    def lookup(self, key: str, rule_ids: Sequence[str]
               ) -> Optional[List[Finding]]:
        """The cached findings, when the entry covers every
        requested rule; None on any miss."""
        e = self._entries.get(key)
        if e is None or not set(rule_ids) <= set(e.get("rules", [])):
            self.misses += 1
            return None
        self.hits += 1
        self._clock += 1
        if e.get("t") != self._clock:
            e["t"] = self._clock
            self._dirty = True
        wanted = set(rule_ids) | {"GL000"}
        return [Finding(rule=f["rule"], path=f["path"],
                        line=int(f["line"]), message=f["message"],
                        symbol=f.get("symbol", ""))
                for f in e.get("findings", [])
                if f["rule"] in wanted]

    def store(self, key: str, rule_ids: Sequence[str],
              findings: Sequence[Finding]) -> None:
        self._clock += 1
        self._entries[key] = {
            "rules": sorted(rule_ids),
            "t": self._clock,
            "findings": [{"rule": f.rule, "path": f.path,
                          "line": f.line, "message": f.message,
                          "symbol": f.symbol} for f in findings]}
        self._dirty = True

    def save(self) -> None:
        if not self._dirty:
            return
        entries = self._entries
        if len(entries) > self.MAX_ENTRIES:
            keep = sorted(entries, key=lambda k: entries[k].get(
                "t", 0), reverse=True)[: self.MAX_ENTRIES]
            entries = {k: entries[k] for k in keep}
        data = {"version": _CACHE_VERSION,
                "fingerprint": self.fingerprint,
                "entries": entries}
        d = os.path.dirname(os.path.abspath(self.path)) or "."
        try:
            fd, tmp = tempfile.mkstemp(dir=d,
                                       prefix=".graftlint_cache.")
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                json.dump(data, f)
            os.replace(tmp, self.path)
        except OSError:
            pass            # a cache that can't write is just cold
