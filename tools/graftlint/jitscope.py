"""Shared jit-context resolution for GL001/GL002/GL003.

Answers, per module: WHICH function bodies are traced (decorated with
or passed to ``jax.jit`` / ``pmap`` / ``shard_map`` / ``lax.scan`` and
friends, resolved through ``functools.partial`` and local name
aliases), and WHERE the jit wrap sites are (with their
``static_argnums`` / ``static_argnames`` / ``donate_argnums`` and the
local name the jitted callable is bound to).

Resolution is purely lexical — no imports are executed. Attribute
targets (``self._step``) are not resolved across methods; the rules
built on this are precise within a scope and silent across ones,
which is the right polarity for a CI gate.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

# canonical dotted names that WRAP a callable for device execution
JIT_WRAPPERS = {
    "jax.jit", "jit", "jax.pmap", "pmap",
    "jax.experimental.pjit.pjit", "pjit",
}
# canonical dotted names whose FIRST argument is a traced body
BODY_TAKERS = {
    "jax.shard_map", "shard_map",
    "jax.experimental.shard_map.shard_map",
    "jax.lax.scan", "lax.scan",
    "jax.lax.while_loop", "lax.while_loop",
    "jax.lax.fori_loop", "lax.fori_loop",
    "jax.lax.cond", "lax.cond",
    "jax.checkpoint", "jax.remat",
}
PARTIAL_NAMES = {"functools.partial", "partial"}
# transforms that preserve "the first argument's body is traced"
TRANSPARENT_TRANSFORMS = {
    "jax.grad", "jax.value_and_grad", "jax.vmap",
    "grad", "value_and_grad", "vmap",
}

FunctionNode = (ast.FunctionDef, ast.AsyncFunctionDef)


def dotted_name(node: ast.AST) -> str:
    """'jax.jit' for Attribute/Name chains; '' when not a plain
    dotted path (calls, subscripts...)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _import_aliases(tree: ast.Module) -> Dict[str, str]:
    """local name -> canonical dotted prefix, from module imports."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


@dataclasses.dataclass
class JitSite:
    """One jit wrap: ``@jax.jit``-style decorator or ``jax.jit(f)``
    call."""
    node: ast.AST                      # the Call or decorator expr
    line: int
    target: Optional[ast.AST]          # resolved FunctionDef / Lambda
    bound_name: str                    # local name the wrap binds
    scope: ast.AST                     # scope the binding lives in
    static_argnums: Tuple[int, ...] = ()
    static_argnames: Tuple[str, ...] = ()
    donate_argnums: Tuple[int, ...] = ()
    wrapper: str = "jax.jit"


class ModuleJitInfo:
    def __init__(self, tree: ast.Module):
        self.tree = tree
        self.aliases = _import_aliases(tree)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        # name -> def/lambda per lexical scope (Module / FunctionDef)
        self.scope_defs: Dict[ast.AST, Dict[str, ast.AST]] = {}
        # name -> aliased-to name per scope (x = y)
        self.scope_aliases: Dict[ast.AST, Dict[str, str]] = {}
        # name -> underlying callable name per scope, through
        # functools.partial (x = partial(f, ...))
        self.scope_partials: Dict[ast.AST, Dict[str, str]] = {}
        self._index_scopes()
        self.sites: List[JitSite] = []
        self.contexts: Set[ast.AST] = set()
        self._find_sites()
        self._close_over_calls()

    # -- scope bookkeeping -------------------------------------------------
    def canon(self, node: ast.AST) -> str:
        """Canonical dotted name with import aliases applied."""
        name = dotted_name(node)
        if not name:
            return ""
        head, _, rest = name.partition(".")
        head = self.aliases.get(head, head)
        return f"{head}.{rest}" if rest else head

    def enclosing_scope(self, node: ast.AST) -> ast.AST:
        cur = self.parents.get(node)
        while cur is not None and not isinstance(
                cur, FunctionNode + (ast.Module, ast.Lambda)):
            cur = self.parents.get(cur)
        return cur if cur is not None else self.tree

    def enclosing_function(self, node: ast.AST
                           ) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, FunctionNode + (ast.Lambda,)):
                return cur
            cur = self.parents.get(cur)
        return None

    def _index_scopes(self) -> None:
        for node in ast.walk(self.tree):
            # methods and class attributes are NOT bare-name
            # resolvable — indexing them into the enclosing scope
            # would let `foo()` resolve to some class's method `foo`
            if isinstance(self.parents.get(node), ast.ClassDef):
                continue
            if isinstance(node, FunctionNode):
                scope = self.enclosing_scope(node)
                self.scope_defs.setdefault(scope, {})[node.name] = node
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                scope = self.enclosing_scope(node)
                tgt = node.targets[0].id
                val = node.value
                if isinstance(val, ast.Name):
                    self.scope_aliases.setdefault(scope, {})[tgt] = \
                        val.id
                elif isinstance(val, ast.Lambda):
                    self.scope_defs.setdefault(scope, {})[tgt] = val
                elif isinstance(val, ast.Call) and \
                        self.canon(val.func) in PARTIAL_NAMES \
                        and val.args:
                    inner = dotted_name(val.args[0])
                    if inner:
                        self.scope_partials.setdefault(
                            scope, {})[tgt] = inner

    def resolve_callable(self, scope: ast.AST, name: str,
                         depth: int = 0) -> Optional[ast.AST]:
        """Find the def/lambda a bare name refers to, walking alias
        and partial chains and enclosing scopes."""
        if depth > 8 or "." in name:
            return None
        cur: Optional[ast.AST] = scope
        while cur is not None:
            defs = self.scope_defs.get(cur, {})
            if name in defs:
                return defs[name]
            part = self.scope_partials.get(cur, {})
            if name in part:
                return self.resolve_callable(cur, part[name],
                                             depth + 1)
            ali = self.scope_aliases.get(cur, {})
            if name in ali:
                return self.resolve_callable(cur, ali[name],
                                             depth + 1)
            cur = None if cur is self.tree else \
                self.enclosing_scope(cur)
        return None

    # -- site discovery ----------------------------------------------------
    @staticmethod
    def _literal_ints(node: Optional[ast.AST]) -> Tuple[int, ...]:
        if node is None:
            return ()
        if isinstance(node, ast.Constant) and isinstance(
                node.value, int) and not isinstance(node.value, bool):
            return (node.value,)
        if isinstance(node, (ast.Tuple, ast.List)):
            out = []
            for e in node.elts:
                if isinstance(e, ast.Constant) and isinstance(
                        e.value, int):
                    out.append(e.value)
            return tuple(out)
        return ()

    @staticmethod
    def _literal_strs(node: Optional[ast.AST]) -> Tuple[str, ...]:
        if node is None:
            return ()
        if isinstance(node, ast.Constant) and isinstance(
                node.value, str):
            return (node.value,)
        if isinstance(node, (ast.Tuple, ast.List)):
            return tuple(e.value for e in node.elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, str))
        return ()

    def _jit_kwargs(self, call: ast.Call) -> dict:
        kw = {k.arg: k.value for k in call.keywords if k.arg}
        return {
            "static_argnums": self._literal_ints(
                kw.get("static_argnums")),
            "static_argnames": self._literal_strs(
                kw.get("static_argnames")),
            "donate_argnums": self._literal_ints(
                kw.get("donate_argnums")),
        }

    def _unwrap_partial(self, node: ast.AST) -> Optional[ast.AST]:
        """partial(f, ...) / bare name / lambda -> resolved callable
        node (for names, via the lexical scope of *node*)."""
        if isinstance(node, ast.Lambda):
            return node
        if isinstance(node, FunctionNode):
            return node
        if isinstance(node, ast.Call) and node.args and \
                self.canon(node.func) in (
                    PARTIAL_NAMES | TRANSPARENT_TRANSFORMS):
            return self._unwrap_partial(node.args[0])
        name = dotted_name(node)
        if name and "." not in name:
            return self.resolve_callable(
                self.enclosing_scope(node), name)
        return None

    def _decorator_jit(self, dec: ast.AST) -> Optional[dict]:
        """None, or the jit kwargs dict when this decorator jits the
        function (``@jax.jit``, ``@jax.jit(...)``,
        ``@functools.partial(jax.jit, ...)``)."""
        if self.canon(dec) in JIT_WRAPPERS:
            return {"static_argnums": (), "static_argnames": (),
                    "donate_argnums": (), "wrapper": self.canon(dec)}
        if isinstance(dec, ast.Call):
            fn = self.canon(dec.func)
            if fn in JIT_WRAPPERS:
                d = self._jit_kwargs(dec)
                d["wrapper"] = fn
                return d
            if fn in PARTIAL_NAMES and dec.args and \
                    self.canon(dec.args[0]) in JIT_WRAPPERS:
                d = self._jit_kwargs(dec)
                d["wrapper"] = self.canon(dec.args[0])
                return d
        return None

    def _bound_name_of(self, call: ast.Call) -> Tuple[str, ast.AST]:
        """Name an ``x = jax.jit(f)`` assignment binds, and its
        scope."""
        parent = self.parents.get(call)
        if isinstance(parent, ast.Assign) and \
                len(parent.targets) == 1 and \
                isinstance(parent.targets[0], ast.Name):
            return parent.targets[0].id, self.enclosing_scope(parent)
        return "", self.enclosing_scope(call)

    def _find_sites(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, FunctionNode):
                for dec in node.decorator_list:
                    d = self._decorator_jit(dec)
                    if d is not None:
                        self.sites.append(JitSite(
                            node=dec, line=dec.lineno, target=node,
                            bound_name=node.name,
                            scope=self.enclosing_scope(node), **d))
                        self.contexts.add(node)
            elif isinstance(node, ast.Call):
                fn = self.canon(node.func)
                if fn in JIT_WRAPPERS and node.args:
                    target = self._unwrap_partial(node.args[0])
                    d = self._jit_kwargs(node)
                    name, scope = self._bound_name_of(node)
                    self.sites.append(JitSite(
                        node=node, line=node.lineno, target=target,
                        bound_name=name, scope=scope,
                        wrapper=fn, **d))
                    if target is not None:
                        self.contexts.add(target)
                elif fn in BODY_TAKERS and node.args:
                    target = self._unwrap_partial(node.args[0])
                    if target is not None:
                        self.contexts.add(target)
                    # while_loop/fori/cond trace every fn arg
                    for extra in node.args[1:]:
                        t = self._unwrap_partial(extra)
                        if t is not None and isinstance(
                                t, FunctionNode + (ast.Lambda,)):
                            if isinstance(extra, (ast.Name, ast.Lambda,
                                                  ast.Call)):
                                self.contexts.add(t)

    def _close_over_calls(self) -> None:
        """Fixpoint: a local function CALLED from a traced body is
        itself traced (one lexical hop at a time)."""
        for _ in range(10):
            grew = False
            for ctx in list(self.contexts):
                for node in ast.walk(ctx):
                    if not (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Name)):
                        continue
                    tgt = self.resolve_callable(
                        self.enclosing_scope(node), node.func.id)
                    if tgt is not None and tgt not in self.contexts:
                        self.contexts.add(tgt)
                        grew = True
            if not grew:
                return

    # -- queries -----------------------------------------------------------
    def in_context(self, node: ast.AST) -> Optional[ast.AST]:
        """Innermost traced function this node sits inside, if any.
        Walks lexical parents; returns the context function node."""
        cur = node
        while cur is not None:
            if cur in self.contexts:
                return cur
            cur = self.parents.get(cur)
        return None

    def context_params(self, fn: ast.AST,
                       static_names: Sequence[str] = (),
                       static_nums: Sequence[int] = ()) -> Set[str]:
        """Parameter names of a traced function that carry TRACED
        values (static args excluded)."""
        if isinstance(fn, ast.Lambda):
            args = fn.args
        elif isinstance(fn, FunctionNode):
            args = fn.args
        else:
            return set()
        names = [a.arg for a in args.posonlyargs + args.args]
        traced = set(names)
        traced -= set(static_names)
        for i in static_nums:
            if 0 <= i < len(names):
                traced.discard(names[i])
        traced.discard("self")
        traced.discard("cls")
        return traced
