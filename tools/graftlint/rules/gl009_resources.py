"""GL009 — resource pairing.

Three acquire/release pairings the serving stack keeps getting
wrong by hand, each class-local and mechanically checkable:

- **per-instance gauge pairing** (the PR 8 ``_sync_views`` leak
  class): a gauge registered with a *dynamic* name (an f-string —
  one gauge per backend/replica) or with non-constant label values
  (``labels={"endpoint": name}``) pins its callback — and through
  the bound method, the whole backend and its device buffers — until
  unregistered. Any class registering such a gauge must also call
  the matching ``unregister``/``unregister_gauge`` with the same
  name skeleton somewhere in the class. Constant-named, unlabeled
  gauges are process-lifetime singletons and exempt.
- **listener pairing**: a class that stores an HTTP listener
  (``ThreadingHTTPServer`` / the shared ``_make_listener``) must
  call ``server_close()`` somewhere — ``shutdown()`` only stops the
  serve loop; without ``server_close`` the bound port leaks until
  GC, and cycling fleet replicas hit EADDRINUSE.
- **unclosed acquisitions**: ``open(...)`` / ``socket.socket(...)``
  / ``ThreadPoolExecutor(...)`` whose result is chained inline
  (``open(p).read()``) or bound to a local that is never closed /
  shut down, never returned, never stored on ``self``, and never
  passed on — a leak on every exit path. ``with`` and
  try/finally-close forms are the clean idioms and stay silent.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.graftlint import jitscope
from tools.graftlint.core import Finding, ParsedModule
from tools.graftlint.rules.base import Rule

_REGISTER_METHODS = {"register_gauge", "gauge"}
_UNREGISTER_METHODS = {"unregister_gauge", "unregister"}
_LISTENER_CTORS = {"ThreadingHTTPServer", "HTTPServer",
                   "_make_listener",
                   "http.server.ThreadingHTTPServer",
                   "http.server.HTTPServer"}
_ACQUIRE_CTORS = {
    "open": ("file", "close"),
    "socket.socket": ("socket", "close"),
    "ThreadPoolExecutor": ("executor", "shutdown"),
    "concurrent.futures.ThreadPoolExecutor": ("executor",
                                              "shutdown"),
    "ProcessPoolExecutor": ("executor", "shutdown"),
    "concurrent.futures.ProcessPoolExecutor": ("executor",
                                               "shutdown"),
}


def _name_skeleton(node: ast.AST) -> Optional[Tuple]:
    """Stable identity for a gauge-name expression: a constant
    string, or the tuple of literal fragments of an f-string (the
    dynamic parts vary per instance; the skeleton pairs the
    register with its unregister)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return ("const", node.value)
    if isinstance(node, ast.JoinedStr):
        parts = tuple(v.value for v in node.values
                      if isinstance(v, ast.Constant))
        return ("fstr",) + parts
    return None


def _is_dynamic(node: ast.AST) -> bool:
    return isinstance(node, ast.JoinedStr)


def _labels_are_dynamic(call: ast.Call) -> bool:
    for k in call.keywords:
        if k.arg != "labels" or not isinstance(k.value, ast.Dict):
            continue
        for v in k.value.values:
            if not isinstance(v, ast.Constant):
                return True
    return False


class ResourcePairingRule(Rule):
    id = "GL009"
    title = "resource-pairing"
    rationale = ("per-instance gauges without an unregister pin dead "
                 "backends; listeners without server_close leak "
                 "ports; unclosed files/sockets/executors leak fds")
    scope = "file"

    def check(self, module: ParsedModule) -> Iterable[Finding]:
        out: List[Finding] = []
        info = module.jit_info
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                out.extend(self._gauge_pairing(module, info, node))
                out.extend(self._listener_pairing(module, info,
                                                  node))
        for fn in [n for n in ast.walk(module.tree)
                   if isinstance(n, jitscope.FunctionNode)]:
            out.extend(self._unclosed_acquisitions(module, info, fn))
        return out

    # ------------------------------------------------------- gauges
    def _gauge_pairing(self, module, info,
                       cls: ast.ClassDef) -> List[Finding]:
        registered: List[Tuple[Tuple, int, str]] = []
        unregistered: Set[Tuple] = set()
        for n in ast.walk(cls):
            if not (isinstance(n, ast.Call) and isinstance(
                    n.func, ast.Attribute) and n.args):
                continue
            skel = _name_skeleton(n.args[0])
            if skel is None:
                continue
            if n.func.attr in _REGISTER_METHODS:
                if n.func.attr == "gauge" and not (
                        _is_dynamic(n.args[0])
                        or _labels_are_dynamic(n)):
                    continue        # process-lifetime singleton
                if n.func.attr == "register_gauge" and not \
                        _is_dynamic(n.args[0]):
                    continue
                registered.append((skel, n.lineno,
                                   ast.unparse(n.args[0])
                                   if hasattr(ast, "unparse")
                                   else str(skel)))
            elif n.func.attr in _UNREGISTER_METHODS:
                unregistered.add(skel)
        out = []
        for skel, line, text in registered:
            if skel in unregistered:
                continue
            out.append(Finding(
                rule=self.id, path=module.relpath, line=line,
                symbol=cls.name,
                message=(
                    f"per-instance gauge {text} is registered by "
                    f"'{cls.name}' but the class never unregisters "
                    "it: each instance generation leaks a gauge "
                    "whose callback pins the dead instance — pair "
                    "it with unregister on the shutdown path")))
        return out

    # ----------------------------------------------------- listeners
    def _listener_pairing(self, module, info,
                          cls: ast.ClassDef) -> List[Finding]:
        created_line = None
        closes = False
        for n in ast.walk(cls):
            if isinstance(n, ast.Call):
                canon = info.canon(n.func)
                if canon.rsplit(".", 1)[-1] in {
                        "ThreadingHTTPServer", "HTTPServer",
                        "_make_listener"}:
                    parent = info.parents.get(n)
                    if isinstance(parent, ast.Assign):
                        created_line = created_line or n.lineno
                if isinstance(n.func, ast.Attribute) and \
                        n.func.attr == "server_close":
                    closes = True
        if created_line is not None and not closes:
            return [Finding(
                rule=self.id, path=module.relpath,
                line=created_line, symbol=cls.name,
                message=(
                    f"'{cls.name}' creates an HTTP listener but "
                    "never calls server_close(): shutdown() only "
                    "stops the serve loop — the bound port leaks "
                    "until GC and a restart on the same port hits "
                    "EADDRINUSE"))]
        return []

    # ------------------------------------------- unclosed acquisitions
    def _unclosed_acquisitions(self, module, info,
                               fn) -> List[Finding]:
        out: List[Finding] = []
        # walk this function's own statements only
        own: List[ast.AST] = []
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            n = stack.pop()
            own.append(n)
            if isinstance(n, jitscope.FunctionNode + (ast.Lambda,
                                                      ast.ClassDef)):
                continue
            stack.extend(ast.iter_child_nodes(n))

        def acquire_kind(call: ast.Call) -> Optional[Tuple[str, str]]:
            canon = info.canon(call.func)
            if canon == "open" and not call.args:
                return None                   # not the builtin form
            return _ACQUIRE_CTORS.get(canon)

        # classify each acquisition call by how its value is used
        assigned: Dict[str, Tuple[int, str, str]] = {}
        released: Set[str] = set()
        escaped: Set[str] = set()
        for n in own:
            if isinstance(n, ast.withitem) and isinstance(
                    n.context_expr, ast.Call):
                continue
            if isinstance(n, ast.Call):
                kind = acquire_kind(n)
                if kind is None:
                    continue
                parent = info.parents.get(n)
                if isinstance(parent, ast.withitem):
                    continue                      # with open(...)
                if isinstance(parent, ast.Attribute):
                    # open(p).read() — closed only at GC
                    out.append(Finding(
                        rule=self.id, path=module.relpath,
                        line=n.lineno, symbol=fn.name,
                        message=(
                            f"{kind[0]} acquired inline "
                            f"(`{info.canon(n.func)}(...)"
                            f".{parent.attr}`) is never closed — "
                            "use `with` so every exit path "
                            "releases it")))
                    continue
                if isinstance(parent, ast.Assign) and len(
                        parent.targets) == 1 and isinstance(
                        parent.targets[0], ast.Name):
                    assigned[parent.targets[0].id] = (
                        n.lineno, kind[0], kind[1])
                elif isinstance(parent, ast.Assign) and isinstance(
                        parent.targets[0], ast.Attribute):
                    pass                           # stored on self
                elif not isinstance(parent, (ast.Return,
                                             ast.withitem)):
                    # passed as an argument / yielded: escapes
                    pass
        for n in own:
            if isinstance(n, ast.Call):
                if isinstance(n.func, ast.Attribute) and isinstance(
                        n.func.value, ast.Name):
                    if n.func.attr in ("close", "shutdown",
                                       "release", "server_close"):
                        released.add(n.func.value.id)
                for a in list(n.args) + [k.value for k in
                                         n.keywords]:
                    if isinstance(a, ast.Name):
                        escaped.add(a.id)
            elif isinstance(n, ast.Return) and isinstance(
                    n.value, ast.Name):
                escaped.add(n.value.id)
            elif isinstance(n, ast.Return) and isinstance(
                    n.value, ast.Tuple):
                for e in n.value.elts:
                    if isinstance(e, ast.Name):
                        escaped.add(e.id)
            elif isinstance(n, ast.Assign) and isinstance(
                    n.value, ast.Name):
                escaped.add(n.value.id)            # re-bound/stored
            elif isinstance(n, ast.withitem) and isinstance(
                    n.context_expr, ast.Name):
                released.add(n.context_expr.id)    # with f: ...
        for name, (line, kind, closer) in sorted(assigned.items()):
            if name in released or name in escaped:
                continue
            out.append(Finding(
                rule=self.id, path=module.relpath, line=line,
                symbol=fn.name,
                message=(
                    f"{kind} '{name}' is acquired but never "
                    f"{closer}()d on any path out of "
                    f"'{fn.name}' — wrap it in `with` or release "
                    "it in `finally`")))
        return out
