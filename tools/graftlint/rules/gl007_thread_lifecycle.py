"""GL007 — thread lifecycle.

Server-lifetime objects (routers, model servers, brokers, control
loops) spawn ``threading.Thread``\\ s that must be *stoppable* and
*stopped*: the fleet soaks found every variant of getting this wrong
by hand, and each one is mechanically detectable per class:

- **unjoined thread**: ``self.X = threading.Thread(...)`` is started
  but no method of the class ever joins it (directly, or through the
  swap idiom ``t, self.X = self.X, None; t.join(...)``). Shutdown
  then returns while the loop still runs — the UI-server/router bug
  class: ``stop()`` asks the listener to exit and never waits for
  it.
- **stale stop event across generations**: a method that creates a
  NEW thread generation (any thread-assigning method other than
  ``__init__``) calls ``self.E.clear()`` on a stop event that some
  other method ``set()``\\ s. The clear races the previous
  (stopping) generation — it can be cleared before the old loop
  observed it, reviving that loop with no handle on it. This is the
  AlertManager revive bug class; the fix is one fresh ``Event`` per
  generation, swapped under the lock.
- **unjoinable server thread**: ``threading.Thread(target=
  <x>.serve_forever).start()`` fired anonymously — the thread is
  never bound to an attribute, so no stop path can ever join it.

Daemon threads are NOT exempt: daemonhood only means the
interpreter won't wait at exit; a server object that is stopped and
restarted within one process still leaks a generation per cycle.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.graftlint import jitscope
from tools.graftlint.core import Finding, ParsedModule
from tools.graftlint.rules.base import Rule

_THREAD_CTORS = {"threading.Thread", "Thread"}
_EVENT_CTORS = {"threading.Event", "Event"}


def _method_of(info, cls: ast.ClassDef,
               node: ast.AST) -> Optional[ast.AST]:
    cur = node
    while cur is not None:
        parent = info.parents.get(cur)
        if parent is cls and isinstance(cur, jitscope.FunctionNode):
            return cur
        cur = parent
    return None


class ThreadLifecycleRule(Rule):
    id = "GL007"
    title = "thread-lifecycle"
    rationale = ("a started thread with no join path outlives its "
                 "owner's shutdown; a stop event shared across "
                 "restart generations revives orphan loops")
    scope = "file"

    def check(self, module: ParsedModule) -> Iterable[Finding]:
        out: List[Finding] = []
        info = module.jit_info
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                out.extend(self._check_class(module, info, node))
        return out

    # ----------------------------------------------------------- class
    def _check_class(self, module, info,
                     cls: ast.ClassDef) -> List[Finding]:
        out: List[Finding] = []
        # thread-typed locals per method: name -> ctor line
        thread_attrs: Dict[str, Tuple[int, str]] = {}  # attr -> (line, method)
        event_attrs: Set[str] = set()
        set_events: Set[str] = set()         # self.E.set() anywhere
        cleared: List[Tuple[str, str, int]] = []  # (attr, method, line)
        joined_attrs: Set[str] = set()
        started_attrs: Set[str] = set()

        methods = [n for n in cls.body
                   if isinstance(n, jitscope.FunctionNode)]
        for m in methods:
            local_threads: Dict[str, int] = {}
            # names locally sourced FROM a self attribute (the swap
            # idiom): name -> attr
            from_attr: Dict[str, str] = {}
            # local thread vars stored TO a self attribute
            # (`t = Thread(...); self.X = t`): name -> attr, so a
            # start/join through the local credits exactly that
            # attribute and no other
            local_to_attr: Dict[str, str] = {}
            # assignments first, calls second: `t.start()` before the
            # `self.X = t` line must still mark X started
            for n in ast.walk(m):
                if isinstance(n, ast.Assign):
                    tgts = n.targets
                    vals = [n.value]
                    if len(tgts) == 1 and isinstance(
                            tgts[0], ast.Tuple) and isinstance(
                            n.value, ast.Tuple) and len(
                            tgts[0].elts) == len(n.value.elts):
                        tgts, vals = tgts[0].elts, n.value.elts
                    for tgt, val in zip(tgts, vals * (
                            len(tgts) if len(vals) == 1 else 1)):
                        self._track_assign(
                            module, info, tgt, val, m,
                            local_threads, from_attr, local_to_attr,
                            thread_attrs, event_attrs)
            for n in ast.walk(m):
                if isinstance(n, ast.Call) and isinstance(
                        n.func, ast.Attribute):
                    f = n.func
                    # self.E.set() / self.X.join() / self.X.start()
                    if isinstance(f.value, ast.Attribute) and \
                            isinstance(f.value.value, ast.Name) and \
                            f.value.value.id == "self":
                        attr = f.value.attr
                        if f.attr == "set":
                            set_events.add(attr)
                        elif f.attr == "clear":
                            cleared.append((attr, m.name, n.lineno))
                        elif f.attr == "join":
                            joined_attrs.add(attr)
                        elif f.attr == "start":
                            started_attrs.add(attr)
                    elif isinstance(f.value, ast.Name):
                        name = f.value.id
                        if f.attr == "join":
                            if name in from_attr:
                                joined_attrs.add(from_attr[name])
                            if name in local_to_attr:
                                joined_attrs.add(local_to_attr[name])
                        elif f.attr == "start" and \
                                name in local_to_attr:
                            # started via the local alias: credits
                            # ONLY the attribute this local was
                            # stored to — an unrelated local thread
                            # starting in the same method must not
                            # mark other attrs started
                            started_attrs.add(local_to_attr[name])
            # anonymous serve_forever threads
            out.extend(self._anonymous_server_threads(
                module, info, cls, m))

        for attr, (line, meth) in sorted(thread_attrs.items()):
            if attr not in started_attrs:
                continue
            if attr in joined_attrs:
                continue
            out.append(Finding(
                rule=self.id, path=module.relpath, line=line,
                symbol=f"{cls.name}.{attr}",
                message=(
                    f"thread 'self.{attr}' started by "
                    f"'{cls.name}' is never joined: no method "
                    "joins it (directly or via the swap idiom), so "
                    "shutdown returns while the loop still runs — "
                    "join it with a timeout on the stop path")))

        # stale stop event: a non-__init__ thread-creating method
        # clears an event that another method sets
        gen_methods = {meth for _a, (_l, meth) in
                       thread_attrs.items() if meth != "__init__"}
        for attr, meth, line in cleared:
            if meth in gen_methods and attr in event_attrs and \
                    attr in set_events:
                out.append(Finding(
                    rule=self.id, path=module.relpath, line=line,
                    symbol=f"{cls.name}.{attr}",
                    message=(
                        f"stop event 'self.{attr}' is clear()ed in "
                        f"'{cls.name}.{meth}' while a new thread "
                        "generation starts, but other methods "
                        "set() it: the clear can race the previous "
                        "(stopping) generation and revive it with "
                        "no handle — create a FRESH Event per "
                        "generation instead of reusing one")))
        return out

    def _track_assign(self, module, info, tgt, val, method,
                      local_threads, from_attr, local_to_attr,
                      thread_attrs, event_attrs) -> None:
        is_thread = (isinstance(val, ast.Call)
                     and info.canon(val.func) in _THREAD_CTORS)
        is_event = (isinstance(val, ast.Call)
                    and info.canon(val.func) in _EVENT_CTORS)
        if isinstance(tgt, ast.Name):
            if is_thread:
                local_threads[tgt.id] = val.lineno
            elif isinstance(val, ast.Attribute) and isinstance(
                    val.value, ast.Name) and val.value.id == "self":
                from_attr[tgt.id] = val.attr
        elif isinstance(tgt, ast.Attribute) and isinstance(
                tgt.value, ast.Name) and tgt.value.id == "self":
            if is_thread:
                thread_attrs[tgt.attr] = (val.lineno, method.name)
            elif is_event:
                event_attrs.add(tgt.attr)
            elif isinstance(val, ast.Name) and \
                    val.id in local_threads:
                thread_attrs[tgt.attr] = (local_threads[val.id],
                                          method.name)
                local_to_attr[val.id] = tgt.attr

    def _anonymous_server_threads(self, module, info, cls,
                                  method) -> List[Finding]:
        out = []
        for n in ast.walk(method):
            if not (isinstance(n, ast.Call) and isinstance(
                    n.func, ast.Attribute) and n.func.attr == "start"):
                continue
            inner = n.func.value
            if not (isinstance(inner, ast.Call)
                    and info.canon(inner.func) in _THREAD_CTORS):
                continue
            tgt = next((k.value for k in inner.keywords
                        if k.arg == "target"), None)
            if isinstance(tgt, ast.Attribute) and \
                    tgt.attr == "serve_forever":
                out.append(Finding(
                    rule=self.id, path=module.relpath,
                    line=inner.lineno,
                    symbol=f"{cls.name}.{method.name}",
                    message=(
                        "server thread started anonymously "
                        "(Thread(target=...serve_forever).start()): "
                        "it is never bound to an attribute, so no "
                        "stop path can join it — store it and join "
                        "it after shutdown()")))
        return out
