"""GL001 — jit purity.

Host side effects inside a traced function run ONCE at trace time
(not per step) or, worse, capture a stale host value into the
compiled program: ``time.time()`` freezes the timestamp,
``random.random()`` freezes the "random" number, a metrics ``inc()``
counts compiles instead of steps, and ``nonlocal``/``global``
mutation desynchronizes host state from device state. The runtime
compile watchdog only notices these when they also change shapes;
this rule rejects them before execution.

Flags, inside any function traced by ``jax.jit`` / ``pmap`` /
``shard_map`` / ``lax.scan``-family (resolved through
``functools.partial`` and local aliases):

- ``time.*`` calls (``time.time``, ``perf_counter``, ``sleep``...)
- host RNG: ``random.*``, ``np.random.*`` (``jax.random`` is fine)
- ``print`` (``jax.debug.print``/``callback`` are the sanctioned
  escape hatches and are not flagged)
- logging calls (``logging.*`` or ``logger.info``-style methods)
- metrics-registry mutations (``.inc/.observe/.record/...`` on a
  receiver that is recognizably a metric object, and ``safe_inc``)
- ``open()``
- ``global`` / ``nonlocal`` declarations
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from tools.graftlint.core import Finding, ParsedModule
from tools.graftlint import jitscope
from tools.graftlint.rules.base import Rule

_LOG_METHODS = {"debug", "info", "warning", "warn", "error",
                "exception", "critical", "log"}
_LOG_RECEIVERS = {"logger", "log", "logging"}
_METRIC_METHODS = {"inc", "dec", "observe", "record", "set_gauge",
                   "safe_inc", "count_shed", "count_error",
                   "count_expired", "time"}
_METRIC_HINTS = ("metric", "registry", "counter", "gauge",
                 "histogram", "stats", "endpoint")


def _symbol(info: jitscope.ModuleJitInfo, ctx: ast.AST) -> str:
    if isinstance(ctx, jitscope.FunctionNode):
        return ctx.name
    return "<lambda>"


class JitPurityRule(Rule):
    id = "GL001"
    title = "jit-purity"
    rationale = ("host side effects inside traced code run at trace "
                 "time, not per step")
    scope = "file"

    def check(self, module: ParsedModule) -> Iterable[Finding]:
        info = module.jit_info
        if not info.contexts:
            return []
        out: List[Finding] = []

        def flag(node: ast.AST, ctx: ast.AST, what: str,
                 hint: str) -> None:
            out.append(Finding(
                rule=self.id, path=module.relpath,
                line=getattr(node, "lineno", 0),
                symbol=_symbol(info, ctx),
                message=f"{what} inside jitted function "
                        f"'{_symbol(info, ctx)}' — {hint}"))

        def visit(node: ast.AST, ctx: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                # a nested def that is itself a registered context is
                # walked on its own pass — skip it here so one
                # offense reports once, under the innermost function
                if child in info.contexts:
                    continue
                self._check_node(child, ctx, info, flag)
                visit(child, ctx)

        for ctx in info.contexts:
            visit(ctx, ctx)
        return self._dedup(out)

    def _check_node(self, node: ast.AST, ctx, info, flag) -> None:
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            kw = ("global" if isinstance(node, ast.Global)
                  else "nonlocal")
            flag(node, ctx,
                 f"{kw} mutation of {', '.join(node.names)}",
                 "host state mutated during tracing runs "
                 "once per compile, not once per step")
        elif isinstance(node, ast.Call):
            self._check_call(node, ctx, info, flag)

    # ------------------------------------------------------------------
    def _check_call(self, node: ast.Call, ctx, info, flag) -> None:
        canon = info.canon(node.func)
        if canon.startswith("jax."):
            return                      # jax.debug.*, jax.random.* ok
        if canon == "print":
            flag(node, ctx, "print()",
                 "prints once at trace time; use jax.debug.print")
            return
        if canon == "open":
            flag(node, ctx, "open()",
                 "file I/O during tracing; hoist out of the jit or "
                 "use jax.debug.callback")
            return
        root = canon.split(".")[0] if canon else ""
        if root == "time":
            flag(node, ctx, f"host clock call '{canon}'",
                 "the timestamp freezes into the compiled program; "
                 "time on the host around the jit boundary")
            return
        if canon.startswith(("random.", "np.random.",
                             "numpy.random.")):
            flag(node, ctx, f"host RNG call '{canon}'",
                 "the value freezes at trace time; thread a "
                 "jax.random key instead")
            return
        if canon.startswith("logging.") or (
                "." in canon
                and canon.rsplit(".", 1)[1] in _LOG_METHODS
                and (canon.split(".")[0] in _LOG_RECEIVERS
                     or canon.split(".")[-2] in _LOG_RECEIVERS
                     or canon.split(".")[0].endswith("logger"))):
            flag(node, ctx, f"logging call '{canon}'",
                 "logs once at trace time; use jax.debug.print or "
                 "log outside the step")
            return
        if canon == "safe_inc" or canon.endswith(".safe_inc"):
            flag(node, ctx, f"metrics call '{canon}'",
                 "counts compiles, not steps; move to the host side "
                 "of the step")
            return
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _METRIC_METHODS:
            recv = jitscope.dotted_name(node.func.value).lower()
            if recv and any(h in recv for h in _METRIC_HINTS):
                flag(node, ctx,
                     f"metrics call '{recv}.{node.func.attr}'",
                     "registry mutation during tracing counts "
                     "compiles, not steps")

    @staticmethod
    def _dedup(findings: List[Finding]) -> List[Finding]:
        seen, out = set(), []
        for f in findings:
            k = (f.path, f.line, f.message)
            if k not in seen:
                seen.add(k)
                out.append(f)
        return out
