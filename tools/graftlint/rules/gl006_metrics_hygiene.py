"""GL006 — metrics hygiene.

Two ways a metrics registry quietly dies in production:

- **Unbounded label cardinality.** A label value that is unique per
  request (trace id, request id, span id, a raw user string) creates
  one time series PER REQUEST: the registry grows without bound, the
  Prometheus exposition becomes megabytes, and every scrape slows the
  server it measures. Per-request identity belongs in an **exemplar**
  (bounded: one per bucket), a span, or the flight recorder — never
  in a label.
- **Instrument creation in hot loops.** ``registry.counter(...)`` is
  get-or-create behind a lock; calling it per iteration to ``inc()``
  churns the registry lock and re-hashes the label key on every
  event. Instruments are created ONCE (module import or ``__init__``)
  and the loop calls ``.inc()``/``.record()`` on the held reference.

What the rule flags:

- any ``labels={...}`` dict (registry calls, metric constructors,
  ``safe_inc``) whose KEY names a per-request id
  (``trace_id``/``request_id``/...) or whose VALUE expression
  mentions one (a name, attribute, ``str(...)`` of one, or an
  f-string interpolating one);
- a registry-method call (``counter``/``gauge``/``histogram``/
  ``adopt``/``register`` on a receiver that is recognizably a
  registry) lexically inside a ``for``/``while`` loop, when the
  created instrument is used inline (``.inc()`` etc.) or discarded —
  storing the result (``self._g[k] = reg.gauge(...)``) is the
  sanctioned init-time pattern and is NOT flagged; ``safe_inc`` is
  the sanctioned never-raise wrapper and is exempt.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from tools.graftlint.core import Finding, ParsedModule
from tools.graftlint.rules.base import Rule

# label keys / identifier substrings that mean "one series per
# request" (or per user) — the cardinality explosion
_BAD_LABEL_KEYS = {"trace_id", "request_id", "span_id", "session_id",
                   "user_id", "uuid", "uid", "prompt", "query"}
_BAD_SUBSTRINGS = ("trace_id", "request_id", "span_id", "session_id",
                   "user_id", "uuid", "traceparent")

_REGISTRY_METHODS = {"counter", "gauge", "histogram", "adopt",
                     "register"}
_METRIC_CTORS = {"Counter", "Gauge", "Histogram",
                 "LatencyHistogram"}
_USE_METHODS = {"inc", "dec", "set", "observe", "record"}


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted source text of a Name/Attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _registry_receiver(func: ast.AST) -> bool:
    """Is this call's receiver recognizably a metrics registry?"""
    if not isinstance(func, ast.Attribute):
        return False
    recv = _dotted(func.value).lower()
    if not recv:
        return False
    last = recv.split(".")[-1]
    return last in ("registry", "reg") or "registry" in last


def _mentions_request_id(node: ast.AST) -> Optional[str]:
    """The first per-request identifier this expression mentions
    (walking names, attributes, f-strings, str()/format calls)."""
    for n in ast.walk(node):
        text = ""
        if isinstance(n, ast.Name):
            text = n.id
        elif isinstance(n, ast.Attribute):
            text = n.attr
        low = text.lower()
        for bad in _BAD_SUBSTRINGS:
            if bad in low:
                return text
    return None


class MetricsHygieneRule(Rule):
    id = "GL006"
    title = "metrics-hygiene"
    rationale = ("per-request label values explode cardinality; "
                 "instrument creation belongs at init time, not in "
                 "hot loops")
    scope = "file"

    def check(self, module: ParsedModule) -> Iterable[Finding]:
        if module.tree is None:
            return []
        out: List[Finding] = []
        self._check_labels(module, out)
        self._check_loop_creation(module, out)
        return out

    # -- unbounded label values ------------------------------------
    def _metric_call(self, call: ast.Call) -> bool:
        f = call.func
        if isinstance(f, ast.Attribute) \
                and f.attr in _REGISTRY_METHODS:
            return True
        name = f.id if isinstance(f, ast.Name) else \
            f.attr if isinstance(f, ast.Attribute) else ""
        return name in _METRIC_CTORS or name == "safe_inc"

    def _check_labels(self, module: ParsedModule,
                      out: List[Finding]) -> None:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call) \
                    or not self._metric_call(node):
                continue
            labels = next((kw.value for kw in node.keywords
                           if kw.arg == "labels"), None)
            if not isinstance(labels, ast.Dict):
                continue
            sym = self._enclosing(module, node)
            for key, value in zip(labels.keys, labels.values):
                if isinstance(key, ast.Constant) \
                        and isinstance(key.value, str) \
                        and key.value.lower() in _BAD_LABEL_KEYS:
                    out.append(Finding(
                        rule=self.id, path=module.relpath,
                        line=key.lineno, symbol=sym,
                        message=f"label key {key.value!r} is a "
                                "per-request identifier — one time "
                                "series per request; use an "
                                "exemplar, a span, or the flight "
                                "recorder instead"))
                    continue
                if value is None:
                    continue
                hit = _mentions_request_id(value)
                if hit is not None:
                    out.append(Finding(
                        rule=self.id, path=module.relpath,
                        line=value.lineno, symbol=sym,
                        message=f"label value reads {hit!r} — a "
                                "per-request identifier as a label "
                                "value explodes cardinality; use an "
                                "exemplar, a span, or the flight "
                                "recorder instead"))

    # -- instrument creation inside loops --------------------------
    def _check_loop_creation(self, module: ParsedModule,
                             out: List[Finding]) -> None:
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            for loop in ast.walk(fn):
                if not isinstance(loop, (ast.For, ast.While)):
                    continue
                for node in ast.walk(loop):
                    if not isinstance(node, ast.Call):
                        continue
                    f = node.func
                    if not (isinstance(f, ast.Attribute)
                            and f.attr in _REGISTRY_METHODS
                            and _registry_receiver(f)):
                        continue
                    if self._stored(module.tree, node):
                        continue      # init-time cache fill: fine
                    out.append(Finding(
                        rule=self.id, path=module.relpath,
                        line=node.lineno, symbol=fn.name,
                        message=f"registry.{f.attr}() inside a loop "
                                f"in '{fn.name}' — get-or-create "
                                "churns the registry lock per "
                                "iteration; create the instrument "
                                "once at init/import time and call "
                                ".inc()/.record() on the held "
                                "reference"))

    @staticmethod
    def _stored(tree: ast.Module, call: ast.Call) -> bool:
        """Is this creation's result stored for reuse (the sanctioned
        init pattern) rather than used inline or discarded?"""
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                if child is call:
                    # the direct parent decides: an Assign stores it;
                    # an Expr discards it; an Attribute receiver
                    # (`reg.counter(...).inc()`) uses it inline
                    if isinstance(parent, (ast.Assign,
                                           ast.AnnAssign,
                                           ast.AugAssign)):
                        return True
                    if isinstance(parent, ast.keyword) \
                            or isinstance(parent, ast.Call):
                        return True    # passed onward: caller stores
                    if isinstance(parent, ast.Return):
                        return True
                    return False
        return False

    @staticmethod
    def _enclosing(module: ParsedModule, node: ast.AST) -> str:
        """Name of the function/class lexically holding ``node``."""
        best = ""
        best_span = None
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                continue
            end = getattr(fn, "end_lineno", fn.lineno)
            if fn.lineno <= node.lineno <= end:
                span = end - fn.lineno
                if best_span is None or span < best_span:
                    best, best_span = fn.name, span
        return best
