"""Rule base class."""

from __future__ import annotations

from typing import Iterable, List

from tools.graftlint.core import Finding, ParsedModule, RepoContext


class Rule:
    id: str = "GL000"
    title: str = ""
    rationale: str = ""
    scope: str = "file"          # "file" | "repo"

    def check(self, module: ParsedModule) -> Iterable[Finding]:
        return []

    def check_repo(self, ctx: RepoContext) -> Iterable[Finding]:
        return []

    def repo_triggered(self, relpath: str) -> bool:
        """Under ``--changed-only``, should this repo-scope rule run
        given that ``relpath`` changed?"""
        return relpath.endswith(".py")
