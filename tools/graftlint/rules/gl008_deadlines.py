"""GL008 — deadline discipline.

A blocking primitive with no timeout, sitting anywhere on a path a
request or a worker loop actually executes, turns one wedged peer
into a wedged thread (and, pooled, a wedged server). The serving
errors module states the contract — "blocking forever is never an
option" — and this rule enforces it interprocedurally:

flag a timeout-less blocking call (``queue.get()``, ``Event.wait()``
/ ``Condition.wait()``, ``lock.acquire()``, socket ``accept``/
``recv`` in classes that never ``settimeout``, ``HTTPConnection``
built without ``timeout=`` — its ``getresponse`` then blocks
forever, ``Popen.communicate()``) **iff** it is reachable from

- an HTTP handler (``do_*`` / ``_handle_*`` methods), or
- a worker loop (any resolved ``threading.Thread`` target and its
  callees),

through the project call graph (``self.method()``, attribute and
local types, annotated returns, callback/ref arguments — see
``callgraph.py``). The same call in a function no handler or worker
reaches is NOT flagged: slow-path tooling may block at will.

The fix is always one of: pass a real deadline, convert to a
heartbeat wait (``while not evt.wait(1.0): <check stop>``) so the
thread stays interruptible, or move the call off the serving path.
"""

from __future__ import annotations

from typing import Dict, Iterable, List

from tools.graftlint import callgraph
from tools.graftlint.core import Finding, RepoContext
from tools.graftlint.rules.base import Rule


class DeadlineDisciplineRule(Rule):
    id = "GL008"
    title = "deadline-discipline"
    rationale = ("a timeout-less blocking call reachable from a "
                 "handler or worker loop wedges the thread when a "
                 "peer dies")
    scope = "repo"

    def check_repo(self, ctx: RepoContext) -> Iterable[Finding]:
        graph = callgraph.get_graph(ctx)
        handler_owner = graph.reachable_from(graph.handler_roots())
        worker_owner = graph.reachable_from(graph.worker_roots())
        out: List[Finding] = []
        for qname in sorted(set(handler_owner) | set(worker_owner)):
            fn = graph.functions.get(qname)
            if fn is None or not fn.blocking:
                continue
            if qname in handler_owner:
                kind = "HTTP handler"
                root = handler_owner[qname]
            else:
                kind = "worker loop"
                root = worker_owner[qname]
            root_fn = graph.functions[root]
            for site in fn.blocking:
                recv = f" on `{site.detail}`" if site.detail else ""
                out.append(Finding(
                    rule=self.id, path=fn.module.relpath,
                    line=site.line, symbol=fn.short,
                    message=(
                        f"blocking `{site.primitive}`{recv} without "
                        f"a timeout is reachable from {kind} "
                        f"'{root_fn.short}' — a wedged peer blocks "
                        "this thread forever; pass a deadline or "
                        "use a heartbeat wait")))
        return out
