"""GL004 — lock discipline.

The serving workers, async checkpoint writer, prefetch threads and
watchdogs share state under ``threading`` locks; the two bug classes
that actually bite are (a) two code paths taking the same pair of
locks in opposite orders — a deadlock that only fires under load —
and (b) an attribute protected by a lock on one path and mutated
bare on another, which is a data race the GIL hides until a
preemption lands between read and write.

Sub-checks (repo scope — the acquisition graph must span files):

- **order**: build a lock-acquisition graph from lexically nested
  ``with <lock>:`` blocks across every analyzed module; any cycle
  (A→B somewhere, B→A elsewhere) is flagged at each participating
  site.
- **reacquire**: ``with self._lock:`` nested inside itself when the
  attribute was created as a plain (non-reentrant)
  ``threading.Lock`` — guaranteed self-deadlock.
- **unlocked-write**: in a class that spawns threads and owns at
  least one lock, an instance attribute assigned both inside a
  ``with``-lock region and outside one (``__init__`` is exempt:
  pre-thread construction is single-threaded). A helper method whose
  every intra-class call site is lock-held counts as lock-held
  itself (one-level call-graph fixpoint), so the
  ``_locked_helper()`` convention does not false-positive.
- **check-then-act**: in the same class population, a method that
  TESTS an instance attribute (``if self._thread is None:``) and
  WRITES it, both outside any lock — the classic double-start race:
  two concurrent callers both pass the test and both act.

Lock identity is lexical: ``<module>.<Class>.<attr>`` for instance
locks, ``<module>.<NAME>`` for module-level locks.
``threading.Lock/RLock/Condition/Semaphore`` (and ``Condition``'s
implicit lock) all count.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.graftlint.core import Finding, ParsedModule, RepoContext
from tools.graftlint import jitscope
from tools.graftlint.rules.base import Rule

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
_THREAD_SPAWNERS = {"threading.Thread", "Thread",
                    "concurrent.futures.ThreadPoolExecutor",
                    "ThreadPoolExecutor"}


def _attr_targets(stmt):
    """Every ``x.attr`` assignment target of a statement, including
    those nested in tuple/list unpacking (``a, self.x = ...``)."""
    targets = (stmt.targets if isinstance(stmt, ast.Assign)
               else [stmt.target])
    for t in targets:
        for node in ast.walk(t):
            if isinstance(node, ast.Attribute) and isinstance(
                    node.value, ast.Name):
                yield node


def _lock_ctor(canon: str) -> Optional[str]:
    """'Lock'/'RLock'/... when the canonical call name constructs a
    threading lock."""
    last = canon.rsplit(".", 1)[-1]
    if last in _LOCK_CTORS and (
            canon.startswith("threading.") or canon == last
            or canon.startswith("multiprocessing.")):
        return last
    return None


class _ClassInfo:
    def __init__(self, module: ParsedModule, node: ast.ClassDef,
                 info: jitscope.ModuleJitInfo):
        self.module = module
        self.node = node
        self.info = info
        self.name = node.name
        self.lock_attrs: Dict[str, str] = {}     # attr -> ctor kind
        self.spawns_threads = False
        self.methods: Dict[str, ast.AST] = {}
        for stmt in node.body:
            if isinstance(stmt, jitscope.FunctionNode):
                self.methods[stmt.name] = stmt
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                canon = info.canon(n.func)
                kind = _lock_ctor(canon)
                if kind:
                    tgt = self._self_attr_target(n)
                    if tgt:
                        self.lock_attrs[tgt] = kind
                if canon in _THREAD_SPAWNERS:
                    self.spawns_threads = True

    def _self_attr_target(self, call: ast.Call) -> Optional[str]:
        parent = self.info.parents.get(call)
        if isinstance(parent, ast.Assign):
            for t in parent.targets:
                if isinstance(t, ast.Attribute) and isinstance(
                        t.value, ast.Name) and t.value.id == "self":
                    return t.attr
        return None


class LockDisciplineRule(Rule):
    id = "GL004"
    title = "lock-discipline"
    rationale = ("inconsistent lock order deadlocks under load; a "
                 "sometimes-locked attribute is a data race")
    scope = "repo"

    def check_repo(self, ctx: RepoContext) -> Iterable[Finding]:
        out: List[Finding] = []
        # lockA -> lockB -> [(path, line, holder_desc)]
        edges: Dict[str, Dict[str, List[Tuple[str, int]]]] = {}
        # pass 1: every module-level lock in the analyzed set, keyed
        # by canonical dotted identity — so pass 2 can recognize a
        # lock IMPORTED from another module (`from a.b import LOCK`)
        # and a genuine cross-file order inversion connects
        per_module = []
        global_locks: Dict[str, str] = {}
        for module in ctx.modules:
            info = module.jit_info
            modname = os.path.splitext(
                module.relpath.replace("/", "."))[0]
            classes = [
                _ClassInfo(module, n, info)
                for n in ast.walk(module.tree)
                if isinstance(n, ast.ClassDef)]
            module_locks = self._module_locks(module, info)
            for name, kind in module_locks.items():
                global_locks[f"{modname}.{name}"] = kind
            per_module.append((module, info, modname, classes,
                               module_locks))
        for module, info, modname, classes, module_locks in \
                per_module:
            by_node = {c.node: c for c in classes}
            self._collect_edges(module, info, modname, by_node,
                                module_locks, global_locks, edges,
                                out)
            for c in classes:
                out.extend(self._unlocked_writes(c))
        out.extend(self._order_cycles(edges))
        return out

    # ------------------------------------------------------------- locks
    @staticmethod
    def _module_locks(module, info) -> Dict[str, str]:
        locks = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call):
                kind = _lock_ctor(info.canon(node.value.func))
                if kind and len(node.targets) == 1 and isinstance(
                        node.targets[0], ast.Name) and isinstance(
                        info.enclosing_scope(node), ast.Module):
                    locks[node.targets[0].id] = kind
        return locks

    def _lock_identity(self, expr: ast.AST, modname: str,
                       cls: Optional[_ClassInfo],
                       module_locks: Dict[str, str],
                       global_locks: Dict[str, str],
                       info) -> Optional[Tuple[str, str]]:
        """(identity, ctor_kind) when ``with <expr>`` takes a known
        lock — a ``self.attr`` lock of this class, a module-level
        lock of this module, or a module-level lock IMPORTED from
        another analyzed module (resolved through the import alias
        map to the same canonical identity its definition
        registered)."""
        if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name) and expr.value.id == "self" \
                and cls is not None and expr.attr in cls.lock_attrs:
            return (f"{modname}.{cls.name}.{expr.attr}",
                    cls.lock_attrs[expr.attr])
        if isinstance(expr, ast.Name) and expr.id in module_locks:
            return (f"{modname}.{expr.id}", module_locks[expr.id])
        canon = info.canon(expr)
        if canon and canon in global_locks:
            return (canon, global_locks[canon])
        return None

    def _collect_edges(self, module, info, modname, by_node,
                       module_locks, global_locks, edges,
                       out) -> None:
        """Walk each function; record held-lock nesting."""

        def owner_class(node) -> Optional[_ClassInfo]:
            cur = info.parents.get(node)
            while cur is not None:
                if cur in by_node:
                    return by_node[cur]
                cur = info.parents.get(cur)
            return None

        def visit(node, held: List[Tuple[str, str]]):
            for child in ast.iter_child_nodes(node):
                # a nested def/lambda runs LATER (thread target,
                # callback): the lexically enclosing lock is not
                # held when its body executes
                if isinstance(child,
                              jitscope.FunctionNode + (ast.Lambda,)):
                    visit(child, [])
                    continue
                new_held = held
                if isinstance(child, ast.With):
                    cls = owner_class(child)
                    acquired = []
                    for item in child.items:
                        ident = self._lock_identity(
                            item.context_expr, modname, cls,
                            module_locks, global_locks, info)
                        if ident:
                            acquired.append(ident)
                    for ident, kind in acquired:
                        for h_ident, _h_kind in held + acquired[
                                :acquired.index((ident, kind))]:
                            if h_ident == ident:
                                if kind == "Lock":
                                    out.append(Finding(
                                        rule=self.id,
                                        path=module.relpath,
                                        line=child.lineno,
                                        symbol=ident,
                                        message=(
                                            f"non-reentrant lock "
                                            f"'{ident}' re-acquired "
                                            "while already held — "
                                            "self-deadlock")))
                                continue
                            edges.setdefault(h_ident, {}).setdefault(
                                ident, []).append(
                                (module.relpath, child.lineno))
                    new_held = held + acquired
                visit(child, new_held)

        visit(module.tree, [])

    def _order_cycles(self, edges) -> List[Finding]:
        out = []
        seen_pairs = set()
        for a, targets in edges.items():
            for b in targets:
                if a == b:
                    continue
                if b in edges and a in edges[b]:
                    pair = tuple(sorted((a, b)))
                    if pair in seen_pairs:
                        continue
                    seen_pairs.add(pair)
                    sites = edges[a][b] + edges[b][a]
                    for path, line in sites:
                        out.append(Finding(
                            rule=self.id, path=path, line=line,
                            symbol=f"{pair[0]}<->{pair[1]}",
                            message=(
                                f"inconsistent lock order between "
                                f"'{pair[0]}' and '{pair[1]}': both "
                                "acquisition orders occur — "
                                "deadlock under contention; pick "
                                "one order")))
        return out

    # ------------------------------------------------- unlocked writes
    def _unlocked_writes(self, c: _ClassInfo) -> List[Finding]:
        if not c.spawns_threads or not c.lock_attrs:
            return []
        info = c.info

        def with_is_lock(w: ast.With) -> bool:
            for item in w.items:
                e = item.context_expr
                if isinstance(e, ast.Attribute) and isinstance(
                        e.value, ast.Name) and e.value.id == "self" \
                        and e.attr in c.lock_attrs:
                    return True
            return False

        def inside_lock(node: ast.AST) -> bool:
            # stop at the first def/lambda boundary: a nested closure
            # (thread target, callback) runs LATER, when the
            # lexically enclosing ``with self._lock:`` is no longer
            # held — only a lock taken inside the same executing
            # function counts
            cur = info.parents.get(node)
            while cur is not None and cur is not c.node:
                if isinstance(cur, ast.With) and with_is_lock(cur):
                    return True
                if isinstance(cur,
                              jitscope.FunctionNode + (ast.Lambda,)):
                    return False
                cur = info.parents.get(cur)
            return False

        def method_of(node: ast.AST) -> Optional[str]:
            cur = node
            while cur is not None:
                parent = info.parents.get(cur)
                if parent is c.node and isinstance(
                        cur, jitscope.FunctionNode):
                    return cur.name
                cur = parent
            return None

        def in_closure(node: ast.AST) -> bool:
            """True when a def/lambda sits strictly between ``node``
            and its class-level method — the node executes on the
            closure's schedule, so the method's lock-held status
            does not transfer to it."""
            cur = info.parents.get(node)
            while cur is not None and cur is not c.node:
                parent = info.parents.get(cur)
                if isinstance(cur,
                              jitscope.FunctionNode + (ast.Lambda,)) \
                        and parent is not c.node:
                    return True
                cur = parent
            return False

        # intra-class call sites:
        # method -> [(caller, locked_ctx, in_closure)]
        calls: Dict[str, List[Tuple[str, bool, bool]]] = {}
        for n in ast.walk(c.node):
            if isinstance(n, ast.Call) and isinstance(
                    n.func, ast.Attribute) and isinstance(
                    n.func.value, ast.Name) and \
                    n.func.value.id == "self" and \
                    n.func.attr in c.methods:
                caller = method_of(n)
                if caller:
                    calls.setdefault(n.func.attr, []).append(
                        (caller, inside_lock(n), in_closure(n)))

        # greatest-fixpoint "this method only ever runs lock-held";
        # a call made from a nested closure inherits nothing from
        # its caller's lock status (the closure runs later)
        locked_m = {m: bool(calls.get(m)) for m in c.methods}
        for _ in range(len(c.methods) + 1):
            changed = False
            for m, sites in calls.items():
                if not locked_m.get(m):
                    continue
                ok = all(held or (locked_m.get(caller, False)
                                  and not in_clo)
                         for caller, held, in_clo in sites)
                if not ok:
                    locked_m[m] = False
                    changed = True
            if not changed:
                break

        # attribute write sites — walk INTO tuple-unpacking targets
        # (`t, self._x = self._x, None` writes self._x too)
        writes: Dict[str, List[Tuple[int, bool]]] = {}
        for n in ast.walk(c.node):
            if isinstance(n, (ast.Assign, ast.AugAssign,
                              ast.AnnAssign)):
                for t in _attr_targets(n):
                    if t.value.id == "self":
                        m = method_of(n)
                        if m is None or m == "__init__":
                            continue
                        if t.attr in c.lock_attrs:
                            continue
                        held = (inside_lock(n)
                                or (locked_m.get(m, False)
                                    and not in_closure(n)))
                        writes.setdefault(t.attr, []).append(
                            (n.lineno, held))
        out = []
        for attr, sites in sorted(writes.items()):
            locked = [s for s in sites if s[1]]
            bare = [s for s in sites if not s[1]]
            if locked and bare:
                for line, _h in bare:
                    out.append(Finding(
                        rule=self.id, path=c.module.relpath,
                        line=line, symbol=f"{c.name}.{attr}",
                        message=(
                            f"attribute 'self.{attr}' of "
                            f"thread-spawning class '{c.name}' is "
                            "written without its lock here but "
                            "under a lock elsewhere — take the "
                            "lock or document the single-writer "
                            "invariant with a suppression")))
        out.extend(self._check_then_act(
            c, inside_lock, method_of, locked_m))
        return out

    def _check_then_act(self, c: _ClassInfo, inside_lock, method_of,
                        locked_m) -> List[Finding]:
        """Per method: a bare ``if``/``while`` TEST of ``self.X``
        plus a bare WRITE of ``self.X`` = a double-start race."""
        info = c.info
        out = []
        for mname, mnode in c.methods.items():
            if mname == "__init__" or locked_m.get(mname):
                continue
            tests: Dict[str, int] = {}
            bare_writes: Set[str] = set()
            for n in ast.walk(mnode):
                if isinstance(n, (ast.If, ast.While)) and \
                        not inside_lock(n):
                    for sub in ast.walk(n.test):
                        if isinstance(sub, ast.Attribute) and \
                                isinstance(sub.value, ast.Name) and \
                                sub.value.id == "self" and \
                                isinstance(sub.ctx, ast.Load):
                            tests.setdefault(sub.attr, n.lineno)
                if isinstance(n, (ast.Assign, ast.AugAssign)):
                    for t in _attr_targets(n):
                        if t.value.id == "self" and \
                                not inside_lock(n):
                            bare_writes.add(t.attr)
            for attr in sorted(set(tests) & bare_writes):
                if attr in c.lock_attrs:
                    continue
                out.append(Finding(
                    rule=self.id, path=c.module.relpath,
                    line=tests[attr], symbol=f"{c.name}.{attr}",
                    message=(
                        f"unlocked check-then-act on "
                        f"'self.{attr}' in "
                        f"'{c.name}.{mname}': the attribute is "
                        "tested and written with no lock held — "
                        "two concurrent callers both pass the "
                        "test; take one of the class's locks "
                        "around the check and the act")))
        return out
