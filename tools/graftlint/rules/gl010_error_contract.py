"""GL010 — serving-error contract.

Two halves of one contract between the typed serving errors, the
HTTP layer, and the README failure matrix:

- **retry hints on admission paths** (interprocedural): the
  backpressure error classes — ``QueueFullError``,
  ``KVPagePoolExhaustedError``, ``ServerClosedError``,
  ``CircuitOpenError``, ``NoReplicaAvailableError`` — map to
  429/503, where the HTTP layer forwards the raiser's
  ``retry_after_s`` as ``Retry-After``. Constructing one of these
  WITHOUT ``retry_after_s=`` anywhere an HTTP handler can reach
  ships a blind-backoff 429/503: routers and load generators lose
  the priced hint the tier system promises. Construction sites
  unreachable from any handler (boot paths, CLI tooling) are
  exempt.
- **status-matrix drift** (doc vs code): the README documents the
  error→status mapping (```SomeError` ... 503`` within a line).
  Every ``except SomeServingError`` arm in the HTTP layer that
  answers with a literal status must agree with the documented
  code. A handler quietly remapping an error class is exactly the
  contract drift PRs 8–13 kept catching in review.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.graftlint import callgraph, jitscope
from tools.graftlint.core import Finding, RepoContext
from tools.graftlint.rules.base import Rule

# 429/503-mapped backpressure errors: the ones whose Retry-After the
# HTTP layer forwards from the raiser
_BACKPRESSURE_ERRORS = {
    "QueueFullError", "KVPagePoolExhaustedError", "ServerClosedError",
    "CircuitOpenError", "NoReplicaAvailableError",
}
_SERVING_ERRORS = _BACKPRESSURE_ERRORS | {
    "ServingError", "DeadlineExceededError", "ModelNotFoundError",
    "ReplicaGoneError", "ReplicaBootError",
}

_DOC_PAIR_RE = re.compile(r"`(?P<err>[A-Z]\w*Error)`|"
                          r"(?<!\d)(?P<code>4\d\d|5\d\d)(?!\d)")


def _doc_matrix(repo: str) -> Dict[str, Set[int]]:
    """README error -> documented status codes, from lines that
    mention both a backticked ``*Error`` and a 4xx/5xx literal."""
    path = os.path.join(repo, "README.md")
    out: Dict[str, Set[int]] = {}
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            text = f.read()
    except OSError:
        return out
    for line in text.splitlines():
        errs, codes = [], []
        for m in _DOC_PAIR_RE.finditer(line):
            if m.group("err"):
                errs.append(m.group("err"))
            else:
                codes.append(int(m.group("code")))
        if len(errs) == 1 and codes:
            # one error + codes on the line: an explicit mapping;
            # multi-error lines are prose, too ambiguous to bind
            out.setdefault(errs[0], set()).update(codes)
    return out


class ErrorContractRule(Rule):
    id = "GL010"
    title = "serving-error-contract"
    rationale = ("a 429/503 without retry_after_s ships a blind "
                 "backoff; a handler remapping a typed error drifts "
                 "from the documented failure matrix")
    scope = "repo"

    def repo_triggered(self, relpath: str) -> bool:
        return relpath.endswith(".py") or relpath == "README.md"

    def check_repo(self, ctx: RepoContext) -> Iterable[Finding]:
        out: List[Finding] = []
        out.extend(self._retry_hints(ctx))
        out.extend(self._status_matrix(ctx))
        return out

    # --------------------------------------------------- retry hints
    def _retry_hints(self, ctx: RepoContext) -> List[Finding]:
        graph = callgraph.get_graph(ctx)
        reach = graph.reachable_from(graph.handler_roots())
        out: List[Finding] = []
        for qname in sorted(reach):
            fn = graph.functions.get(qname)
            if fn is None:
                continue
            for site in fn.errors:
                if site.error not in _BACKPRESSURE_ERRORS:
                    continue
                if site.has_retry_after:
                    continue
                root = graph.functions[reach[qname]]
                out.append(Finding(
                    rule=self.id, path=fn.module.relpath,
                    line=site.line, symbol=fn.short,
                    message=(
                        f"{site.error} constructed without "
                        f"retry_after_s on an admission path "
                        f"(reachable from '{root.short}'): the "
                        "429/503 goes out with a blind Retry-After "
                        "— pass the raiser's backoff hint")))
        return out

    # ------------------------------------------------- status matrix
    def _status_matrix(self, ctx: RepoContext) -> List[Finding]:
        doc = _doc_matrix(ctx.repo)
        if not doc:
            return []
        out: List[Finding] = []
        for module in ctx.modules:
            info = module.jit_info
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.ExceptHandler) or \
                        node.type is None:
                    continue
                names = self._caught_names(node.type)
                codes = self._sent_codes(node)
                if not codes:
                    continue
                for name in names:
                    if name not in _SERVING_ERRORS or name not in doc:
                        continue
                    bad = codes - doc[name]
                    for code in sorted(bad):
                        out.append(Finding(
                            rule=self.id, path=module.relpath,
                            line=node.lineno, symbol=name,
                            message=(
                                f"handler maps {name} to HTTP "
                                f"{code}, but the README failure "
                                f"matrix documents "
                                f"{sorted(doc[name])} — fix the "
                                "handler or the matrix")))
        return out

    @staticmethod
    def _caught_names(t: ast.AST) -> List[str]:
        nodes = t.elts if isinstance(t, ast.Tuple) else [t]
        out = []
        for n in nodes:
            if isinstance(n, ast.Name):
                out.append(n.id)
            elif isinstance(n, ast.Attribute):
                out.append(n.attr)
        return out

    @staticmethod
    def _sent_codes(handler: ast.ExceptHandler) -> Set[int]:
        """Literal 4xx/5xx status arguments of calls made in the
        except body (``err(429, e)``, ``self._send(503, ...)``)."""
        codes: Set[int] = set()
        for n in ast.walk(handler):
            if isinstance(n, ast.Call):
                for a in n.args[:1]:
                    if isinstance(a, ast.Constant) and isinstance(
                            a.value, int) and 400 <= a.value < 600:
                        codes.add(a.value)
        return codes
