"""GL002 — recompile hazards.

The ROADMAP's dispatch-overhead work drives post-warmup compile count
to ZERO; these are the call patterns that silently regress that. The
runtime ``observability/compile_watch.py`` watchdog only fires after
a recompile already cost its ~seconds; this rule rejects the hazard
statically.

Sub-checks:

- **static-shape**: a call to a jitted callable passes a value
  derived from a data shape (``x.shape[...]``) or an f-string into a
  ``static_argnums``/``static_argnames`` position without going
  through a bucketing helper (any callable whose name mentions
  ``bucket``/``pow2``) — every distinct value compiles a fresh
  executable.
- **traced-branch**: Python ``if``/``while`` on a traced parameter
  inside a jitted body. Shape/dtype/None tests are allowed (static
  under tracing); a value test either recompiles per value or fails
  tracing outright — use ``lax.cond``/``jnp.where``.
- **jit-in-loop**: ``jax.jit``/``pmap`` wrap evaluated inside a
  ``for``/``while`` body — a fresh executable (and cache entry)
  per iteration.
- **raw-shape-key**: an executable cache subscripted with a raw
  ``.shape`` expression (``cache[x.shape]``) — unbucketed shapes
  make the cache (and compile count) unbounded.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from tools.graftlint.core import Finding, ParsedModule
from tools.graftlint import jitscope
from tools.graftlint.rules.base import Rule

_STATIC_UNDER_TRACE = {"shape", "ndim", "dtype", "size"}
_BUCKET_HINTS = ("bucket", "pow2")
_CACHE_HINTS = ("cache", "compiled", "executables", "programs")


def _contains_shape(node: ast.AST) -> bool:
    return any(isinstance(n, ast.Attribute) and n.attr == "shape"
               for n in ast.walk(node))


def _bucketed(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            name = jitscope.dotted_name(n.func).lower()
            if any(h in name for h in _BUCKET_HINTS):
                return True
    return False


class RecompileHazardRule(Rule):
    id = "GL002"
    title = "recompile-hazard"
    rationale = ("shape-derived static args, traced branches and "
                 "per-iteration jit wraps each compile a fresh "
                 "executable")
    scope = "file"

    def check(self, module: ParsedModule) -> Iterable[Finding]:
        info = module.jit_info
        out: List[Finding] = []
        out += self._static_shape(module, info)
        out += self._traced_branch(module, info)
        out += self._jit_in_loop(module, info)
        out += self._raw_shape_key(module, info)
        return out

    # --- static args fed from shapes / f-strings ----------------------
    def _static_shape(self, module, info) -> List[Finding]:
        out = []
        donors = {}           # (scope, name) -> JitSite
        for site in info.sites:
            if site.bound_name and (site.static_argnums
                                    or site.static_argnames):
                donors[(site.scope, site.bound_name)] = site
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)):
                continue
            site = self._lookup(donors, info, node)
            if site is None:
                continue
            hazards = []
            for i in site.static_argnums:
                if i < len(node.args):
                    hazards.append((node.args[i], f"position {i}"))
            for kw in node.keywords:
                if kw.arg in site.static_argnames:
                    hazards.append((kw.value, f"'{kw.arg}'"))
            for expr, where in hazards:
                if isinstance(expr, ast.JoinedStr):
                    out.append(self._f(
                        module, node,
                        f"f-string passed as static arg {where} of "
                        f"jitted '{node.func.id}' — every distinct "
                        "string compiles a fresh executable"))
                elif _contains_shape(expr) and not _bucketed(expr):
                    out.append(self._f(
                        module, node,
                        f"shape-derived value passed as static arg "
                        f"{where} of jitted '{node.func.id}' without "
                        "bucketing — compiles per distinct shape"))
        return out

    @staticmethod
    def _lookup(donors, info, call) -> Optional[jitscope.JitSite]:
        name = call.func.id
        scope = info.enclosing_scope(call)
        while scope is not None:
            if (scope, name) in donors:
                return donors[(scope, name)]
            if scope is info.tree:
                return None
            scope = info.enclosing_scope(scope)
        return None

    # --- Python branches on traced values -----------------------------
    def _traced_branch(self, module, info) -> List[Finding]:
        out = []
        for site in info.sites:
            if site.target is None or not isinstance(
                    site.target, jitscope.FunctionNode):
                continue
            traced = info.context_params(
                site.target, site.static_argnames,
                site.static_argnums)
            if not traced:
                continue
            for node in ast.walk(site.target):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                bad = self._traced_test_name(node.test, traced)
                if bad:
                    kw = "if" if isinstance(node, ast.If) else "while"
                    out.append(self._f(
                        module, node,
                        f"Python `{kw}` on traced value '{bad}' "
                        f"inside jitted '{site.target.name}' — "
                        "either fails tracing or recompiles per "
                        "value; use lax.cond/lax.while_loop/"
                        "jnp.where",
                        symbol=site.target.name))
        return out

    @staticmethod
    def _traced_test_name(test: ast.AST, traced) -> str:
        """Name of a traced param the test branches on, or ''.
        Shape/dtype/None/isinstance tests are static and fine."""
        if isinstance(test, ast.Compare) and any(
                isinstance(op, (ast.Is, ast.IsNot))
                for op in test.ops):
            return ""
        for n in ast.walk(test):
            if isinstance(n, ast.Call):
                name = jitscope.dotted_name(n.func)
                if name in ("isinstance", "len", "hasattr",
                            "getattr", "callable"):
                    return ""
            if isinstance(n, ast.Attribute) and \
                    n.attr in _STATIC_UNDER_TRACE:
                return ""
        for n in ast.walk(test):
            if isinstance(n, ast.Name) and \
                    isinstance(n.ctx, ast.Load) and n.id in traced:
                return n.id
        return ""

    # --- jit() evaluated inside a loop --------------------------------
    def _jit_in_loop(self, module, info) -> List[Finding]:
        out = []
        for site in info.sites:
            if not isinstance(site.node, ast.Call):
                continue
            cur = info.parents.get(site.node)
            while cur is not None and not isinstance(
                    cur, jitscope.FunctionNode + (ast.Lambda,)):
                if isinstance(cur, (ast.For, ast.While)):
                    out.append(self._f(
                        module, site.node,
                        f"{site.wrapper}(...) evaluated inside a "
                        "loop — a fresh executable (and compile) "
                        "per iteration; hoist the wrap out of the "
                        "loop"))
                    break
                cur = info.parents.get(cur)
        return out

    # --- executable caches keyed on raw shapes ------------------------
    def _raw_shape_key(self, module, info) -> List[Finding]:
        out = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Subscript):
                continue
            base = jitscope.dotted_name(node.value).lower()
            if not base or not any(h in base.split(".")[-1]
                                   for h in _CACHE_HINTS):
                continue
            key = node.slice
            if _contains_shape(key) and not _bucketed(key):
                out.append(self._f(
                    module, node,
                    f"cache '{base}' keyed on a raw .shape — "
                    "unbucketed shape keys make the executable "
                    "cache (and compile count) unbounded; bucket "
                    "the shape (pow2) first"))
        return out

    def _f(self, module, node, msg, symbol="") -> Finding:
        return Finding(rule=self.id, path=module.relpath,
                       line=getattr(node, "lineno", 0),
                       symbol=symbol, message=msg)
