"""GL011 — chaos-site coverage.

``chaos/injector.py`` is the single authority for injection sites
(``SITES``) and their kinds (``SITE_KINDS``); the README documents
them; the stack threads them as string literals at ``chaos.hit`` /
``step_fault`` / ``file_fault`` call sites. Three artifacts, one
truth — and three drift modes, checked three-way:

- **declared but never threaded**: a site in ``SITES`` with no
  ``hit``/``step_fault``/``file_fault`` call site anywhere in the
  analyzed tree — a fault plan naming it installs cleanly and
  injects nothing.
- **threaded but undeclared**: a call-site literal missing from
  ``SITES`` — ``hit("typo.site")`` silently never fires (plan
  validation can't name it), the worst kind of dead chaos coverage.
- **doc drift**: a declared site missing from the README fault-
  injection table, or a site-looking token documented there that
  ``SITES`` does not declare (the GL005 token check, made
  bidirectional and site-complete).
- **kind never interpreted**: a site-specific kind in
  ``SITE_KINDS`` (beyond the generic crash/hang/slow/error/enospc
  handled centrally by ``step_fault``) that never appears in a
  ``.kind`` comparison or membership test — the plan accepts it,
  the call site ignores it, and it "fires" as a no-op.

The network chaos proxy (``chaos/netproxy.py``) gets the same
treatment, three-way over ``NET_KINDS``: the dict IS the plan-parse
validation set, so every key must also (a) appear in a ``.kind``
comparison somewhere (the proxy actually interprets it) and (b) sit
in the README's network-fault kind table — and every kind the table
documents must be a ``NET_KINDS`` key, or a plan copied from the
docs fails to parse. ``NET_SITES`` entries must appear in the README
like injector sites must (GL005 owns the reverse direction).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Set

from tools.graftlint.core import Finding, ParsedModule, RepoContext
from tools.graftlint.rules.base import Rule

_INJECTOR_RELPATH = "deeplearning4j_tpu/chaos/injector.py"
_NETPROXY_RELPATH = "deeplearning4j_tpu/chaos/netproxy.py"
_HIT_FUNCS = {"hit", "step_fault", "file_fault",
              # chaos.retry's wrapper: retrying_io(site, fn) hits
              # the site through the shared retry policy
              "retrying_io"}
# generic kinds are applied centrally by step_fault/file_fault
_CENTRAL_KINDS = {"crash", "hang", "slow", "error", "enospc",
                  "truncate", "corrupt"}
_DOC_SITE_RE = re.compile(r"`([a-z][a-z0-9_]*(?:\.[a-z0-9_]+)+)`")
# the README network-fault kind table: a markdown table whose header
# row's first column is literally "kind"; each following row's first
# cell is one backticked kind name
_NET_TABLE_HEADER_RE = re.compile(r"^\|\s*kind\s*\|", re.IGNORECASE)
_NET_ROW_RE = re.compile(r"^\|\s*`([a-z][a-z0-9_]*)`\s*\|")


class ChaosCoverageRule(Rule):
    id = "GL011"
    title = "chaos-site-coverage"
    rationale = ("an undeclared or unthreaded chaos site is dead "
                 "fault coverage that still looks installed")
    scope = "repo"

    def repo_triggered(self, relpath: str) -> bool:
        return relpath.endswith(".py") or relpath == "README.md"

    # ------------------------------------------------------------------
    def check_repo(self, ctx: RepoContext) -> Iterable[Finding]:
        injector = next((m for m in ctx.modules
                         if m.relpath == _INJECTOR_RELPATH), None)
        if injector is None:
            return []        # fixture runs / partial trees: no gate
        declared = self._declared(injector)
        if declared is None:
            return []
        sites, kinds_by_site, sites_line, kinds_line = declared
        threaded = self._threaded(ctx)
        kind_literals = self._kind_comparisons(ctx)
        doc_sites = self._doc_sites(ctx.repo)
        out: List[Finding] = []

        for site in sorted(sites):
            if site not in threaded:
                out.append(Finding(
                    rule=self.id, path=injector.relpath,
                    line=sites_line, symbol=site,
                    message=(
                        f"chaos site '{site}' is declared in SITES "
                        "but never threaded: no hit()/step_fault()/"
                        "file_fault() call site names it — a plan "
                        "naming it installs cleanly and injects "
                        "nothing")))
        for site, (relpath, line) in sorted(threaded.items()):
            if site not in sites:
                out.append(Finding(
                    rule=self.id, path=relpath, line=line,
                    symbol=site,
                    message=(
                        f"chaos call site names '{site}' which "
                        "SITES does not declare: plans cannot "
                        "target it and a typo here silently never "
                        "fires — declare it or fix the literal")))
        if doc_sites is not None:
            for site in sorted(sites):
                if site not in doc_sites:
                    out.append(Finding(
                        rule=self.id, path="README.md", line=0,
                        symbol=site,
                        message=(
                            f"chaos site '{site}' is declared and "
                            "threaded but missing from the README "
                            "fault-injection table")))
        for site in sorted(kinds_by_site):
            for kind in sorted(kinds_by_site[site]
                               - _CENTRAL_KINDS):
                if kind not in kind_literals:
                    out.append(Finding(
                        rule=self.id, path=injector.relpath,
                        line=kinds_line, symbol=f"{site}/{kind}",
                        message=(
                            f"site-specific chaos kind '{kind}' "
                            f"(site '{site}') is declared in "
                            "SITE_KINDS but no call site ever "
                            "compares fault.kind against it — it "
                            "fires as a silent no-op")))
        out.extend(self._check_netproxy(ctx, kind_literals,
                                        doc_sites))
        return out

    # ---------------------------------------------------- net proxy
    def _check_netproxy(self, ctx: RepoContext,
                        kind_literals: Set[str],
                        doc_sites: Optional[Set[str]]
                        ) -> List[Finding]:
        module = next((m for m in ctx.modules
                       if m.relpath == _NETPROXY_RELPATH), None)
        if module is None:
            return []
        declared = self._net_declared(module)
        if declared is None:
            return []
        net_sites, net_kinds, sites_line, kinds_line = declared
        out: List[Finding] = []
        # NET_KINDS is the plan-parse validation set; every key must
        # also be interpreted by the proxy's data path
        for kind in sorted(net_kinds):
            if kind not in kind_literals:
                out.append(Finding(
                    rule=self.id, path=module.relpath,
                    line=kinds_line, symbol=kind,
                    message=(
                        f"network-fault kind '{kind}' is accepted "
                        "at plan-parse time (NET_KINDS) but the "
                        "proxy never compares a fault's kind "
                        "against it — it fires as a silent no-op")))
        doc_kinds = self._net_doc_kinds(ctx.repo)
        if doc_kinds is not None:
            for kind in sorted(set(net_kinds) - set(doc_kinds)):
                out.append(Finding(
                    rule=self.id, path="README.md", line=0,
                    symbol=kind,
                    message=(
                        f"network-fault kind '{kind}' is declared "
                        "in NET_KINDS but missing from the README "
                        "network-fault kind table")))
            for kind in sorted(set(doc_kinds) - set(net_kinds)):
                out.append(Finding(
                    rule=self.id, path="README.md",
                    line=doc_kinds[kind], symbol=kind,
                    message=(
                        f"the README network-fault kind table "
                        f"documents '{kind}' but NET_KINDS does not "
                        "declare it — a plan copied from the docs "
                        "fails to parse")))
        if doc_sites is not None:
            for site in sorted(net_sites):
                if site not in doc_sites:
                    out.append(Finding(
                        rule=self.id, path="README.md", line=0,
                        symbol=site,
                        message=(
                            f"network-chaos site '{site}' is "
                            "declared in NET_SITES but missing "
                            "from the README network fault-"
                            "injection docs")))
        return out

    def _net_declared(self, module: ParsedModule):
        sites: Set[str] = set()
        kinds: Set[str] = set()
        sites_line = kinds_line = 0
        for node in module.tree.body:
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = ([node.target] if isinstance(node,
                                                   ast.AnnAssign)
                       else node.targets)
            name = next((t.id for t in targets
                         if isinstance(t, ast.Name)), "")
            value = node.value
            if not isinstance(value, ast.Dict):
                continue
            keys = {k.value for k in value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
            if name == "NET_SITES":
                sites, sites_line = keys, node.lineno
            elif name == "NET_KINDS":
                kinds, kinds_line = keys, node.lineno
        if not sites and not kinds:
            return None
        return sites, kinds, sites_line, kinds_line

    def _net_doc_kinds(self, repo: str) -> Optional[Dict[str, int]]:
        """First-column backticked tokens of the README table whose
        header column is ``kind`` — ``{kind: line_no}``."""
        path = os.path.join(repo, "README.md")
        try:
            with open(path, encoding="utf-8",
                      errors="replace") as f:
                lines = f.read().splitlines()
        except OSError:
            return None
        out: Dict[str, int] = {}
        in_table = False
        for i, line in enumerate(lines, 1):
            if _NET_TABLE_HEADER_RE.match(line):
                in_table = True
                continue
            if in_table:
                if not line.startswith("|"):
                    in_table = False
                    continue
                m = _NET_ROW_RE.match(line)
                if m:
                    out.setdefault(m.group(1), i)
        return out

    # ------------------------------------------------------- declared
    def _declared(self, injector: ParsedModule):
        sites: Set[str] = set()
        kinds: Dict[str, Set[str]] = {}
        sites_line = kinds_line = 0
        for node in injector.tree.body:
            if not (isinstance(node, ast.AnnAssign) or isinstance(
                    node, ast.Assign)):
                continue
            targets = ([node.target] if isinstance(node,
                                                   ast.AnnAssign)
                       else node.targets)
            name = next((t.id for t in targets
                         if isinstance(t, ast.Name)), "")
            value = node.value
            if name == "SITES" and isinstance(value, ast.Dict):
                sites_line = node.lineno
                for k in value.keys:
                    if isinstance(k, ast.Constant) and isinstance(
                            k.value, str):
                        sites.add(k.value)
            elif name == "SITE_KINDS" and isinstance(value,
                                                     ast.Dict):
                kinds_line = node.lineno
                for k, v in zip(value.keys, value.values):
                    if not (isinstance(k, ast.Constant)
                            and isinstance(k.value, str)):
                        continue
                    kinds[k.value] = {
                        n.value for n in ast.walk(v)
                        if isinstance(n, ast.Constant)
                        and isinstance(n.value, str)}
        if not sites:
            return None
        return sites, kinds, sites_line, kinds_line

    # ------------------------------------------------------- threaded
    def _threaded(self, ctx: RepoContext
                  ) -> Dict[str, tuple]:
        out: Dict[str, tuple] = {}
        for module in ctx.modules:
            if module.relpath == _INJECTOR_RELPATH:
                continue     # the injector's own helpers don't count
            info = module.jit_info
            for node in ast.walk(module.tree):
                if not (isinstance(node, ast.Call) and node.args):
                    continue
                canon = info.canon(node.func)
                if canon.rsplit(".", 1)[-1] not in _HIT_FUNCS:
                    continue
                a = node.args[0]
                if isinstance(a, ast.Constant) and isinstance(
                        a.value, str) and "." in a.value:
                    out.setdefault(a.value,
                                   (module.relpath, node.lineno))
        return out

    def _kind_comparisons(self, ctx: RepoContext) -> Set[str]:
        """String literals compared against a ``.kind`` attribute
        anywhere in the tree (== / in (...))."""
        out: Set[str] = set()
        for module in ctx.modules:
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Compare):
                    continue
                sides = [node.left] + list(node.comparators)
                if not any(isinstance(s, ast.Attribute)
                           and s.attr == "kind" for s in sides):
                    continue
                for s in sides:
                    for c in ast.walk(s):
                        if isinstance(c, ast.Constant) and \
                                isinstance(c.value, str):
                            out.add(c.value)
        return out

    # ------------------------------------------------------------ docs
    def _doc_sites(self, repo: str) -> Optional[Set[str]]:
        path = os.path.join(repo, "README.md")
        try:
            with open(path, encoding="utf-8",
                      errors="replace") as f:
                text = f.read()
        except OSError:
            return None
        return set(_DOC_SITE_RE.findall(text))
    # the "documented but undeclared" direction is GL005's token
    # check and stays there — this rule owns completeness of the
    # declared set
