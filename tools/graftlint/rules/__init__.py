"""Rule registry. Each rule module defines one class with a unique
``id``; importing this package registers all of them."""

from tools.graftlint.rules.base import Rule
from tools.graftlint.rules.gl001_jit_purity import JitPurityRule
from tools.graftlint.rules.gl002_recompile import RecompileHazardRule
from tools.graftlint.rules.gl003_donation import DonationAuditRule
from tools.graftlint.rules.gl004_locks import LockDisciplineRule
from tools.graftlint.rules.gl005_literal_drift import LiteralDriftRule
from tools.graftlint.rules.gl006_metrics_hygiene import (
    MetricsHygieneRule)

ALL_RULES = {cls.id: cls for cls in (
    JitPurityRule, RecompileHazardRule, DonationAuditRule,
    LockDisciplineRule, LiteralDriftRule, MetricsHygieneRule)}

__all__ = ["ALL_RULES", "Rule"]
