"""Rule registry. Each rule module defines one class with a unique
``id``; importing this package registers all of them."""

from tools.graftlint.rules.base import Rule
from tools.graftlint.rules.gl001_jit_purity import JitPurityRule
from tools.graftlint.rules.gl002_recompile import RecompileHazardRule
from tools.graftlint.rules.gl003_donation import DonationAuditRule
from tools.graftlint.rules.gl004_locks import LockDisciplineRule
from tools.graftlint.rules.gl005_literal_drift import LiteralDriftRule
from tools.graftlint.rules.gl006_metrics_hygiene import (
    MetricsHygieneRule)
from tools.graftlint.rules.gl007_thread_lifecycle import (
    ThreadLifecycleRule)
from tools.graftlint.rules.gl008_deadlines import (
    DeadlineDisciplineRule)
from tools.graftlint.rules.gl009_resources import ResourcePairingRule
from tools.graftlint.rules.gl010_error_contract import (
    ErrorContractRule)
from tools.graftlint.rules.gl011_chaos_coverage import (
    ChaosCoverageRule)

ALL_RULES = {cls.id: cls for cls in (
    JitPurityRule, RecompileHazardRule, DonationAuditRule,
    LockDisciplineRule, LiteralDriftRule, MetricsHygieneRule,
    ThreadLifecycleRule, DeadlineDisciplineRule, ResourcePairingRule,
    ErrorContractRule, ChaosCoverageRule)}

__all__ = ["ALL_RULES", "Rule"]
