"""GL005 — literal drift (absorbs ``tools/check_perf_claims.py``).

Docs drift from code silently: a README that cites a renamed metric,
a chaos site that no longer exists, or a perf multiplier no bench
artifact ever measured is worse than no README. Three sub-checks,
unchanged in semantics from the standalone lint they generalize:

- **perf claims**: every ``N.Nx``/``N.N×`` multiplier in README.md /
  COMPONENTS.md must match an explicit ``*vs_*`` ratio key in
  BENCH_DETAIL.json or a ratio of two same-(unit, metric-family)
  config values, at the claim's own precision. Lines containing
  "target" are exempt (a goal is not a measurement).
- **metric names**: every backticked ``*_total``/``*_seconds``/
  ``*_bytes``/``*_depth``/``*_firing``/``*_state`` token in the docs
  must exist as a metric-name string literal under the package
  (f-string templates match as wildcards). Fleet-level metrics don't
  all carry a typed suffix (``fleet_targets_up``), so any backticked
  ``fleet_*`` token is held to the same must-exist bar.
- **chaos sites**: inside doc sections headed fault-injection/chaos,
  every backticked dotted token must exist as a string literal under
  the package.

The legacy functions (``check``, ``check_metric_names``,
``check_site_names``) are kept with their list-of-strings API —
``tools/check_perf_claims.py`` is now a shim over them.
"""

from __future__ import annotations

import itertools
import json
import os
import re
from typing import Iterable, List, Tuple

from tools.graftlint.core import (Finding, PACKAGE_DIR, ParsedModule,
                                  RepoContext)
from tools.graftlint.rules.base import Rule

DOC_FILES = ["README.md", "COMPONENTS.md"]
ARTIFACT = "BENCH_DETAIL.json"

# an N.Nx multiplier claim: requires a decimal point (plain "2x256"
# tensor shapes and "8x" core counts are not perf claims in this
# repo's docs; the measured-claim convention is one decimal or more)
CLAIM_RE = re.compile(r"(\d+\.\d+)\s*[x×]")

METRIC_SUFFIXES = ("_total", "_seconds", "_bytes", "_depth",
                   "_firing", "_state")
# the fleet collector's gauges don't all carry a typed suffix
# (fleet_targets_up), so the whole prefix family counts as metric
# citations too
METRIC_PREFIXES = ("fleet_",)
_SUFFIX_ALT = "|".join(METRIC_SUFFIXES)
_PREFIX_ALT = "|".join(METRIC_PREFIXES)
DOC_METRIC_RE = re.compile(
    r"`([a-z][a-z0-9_]*(?:%s)|(?:%s)[a-z0-9_]+)`"
    % (_SUFFIX_ALT, _PREFIX_ALT))
SRC_METRIC_RE = re.compile(
    r"""["']([A-Za-z0-9_{}]*(?:%s)|(?:%s)[A-Za-z0-9_{}]+)["']"""
    % (_SUFFIX_ALT, _PREFIX_ALT))

DOC_SITE_RE = re.compile(r"`([a-z][a-z0-9_]*(?:\.[a-z0-9_]+)+)`")
SRC_SITE_RE = re.compile(
    r"""["']([a-z][a-z0-9_]*(?:\.[a-z0-9_]+)+)["']""")
_SITE_EXT_SKIP = {"py", "json", "jsonl", "md", "zip", "npz", "npy",
                  "txt", "ini", "csv", "bin", "gz", "log", "html",
                  "h5", "yaml", "yml"}


# ---------------------------------------------------------------------------
# perf claims
# ---------------------------------------------------------------------------

def _collect_ratio_keys(obj, out: List[float]) -> None:
    if isinstance(obj, dict):
        for k, v in obj.items():
            if "vs_" in str(k) and isinstance(v, (int, float)) \
                    and not isinstance(v, bool):
                out.append(float(v))
            else:
                _collect_ratio_keys(v, out)
    elif isinstance(obj, list):
        for v in obj:
            _collect_ratio_keys(v, out)


def measured_numbers(detail: dict) -> List[float]:
    """Legitimate multiplier sources only: explicit ``*vs_*`` ratio
    keys anywhere in the artifact, plus cross-config ``value`` ratios
    within one (unit, metric-family) pair — NOT every raw number."""
    out: List[float] = []
    _collect_ratio_keys(detail, out)
    configs = detail.get("configs", [])
    by_family = {}
    for c in configs:
        if isinstance(c.get("value"), (int, float)) and c.get("unit"):
            family = (c["unit"],
                      str(c.get("metric", "")).split(" ")[0])
            by_family.setdefault(family, []).append(float(c["value"]))
    for vals in by_family.values():
        for a, b in itertools.permutations(vals, 2):
            if b:
                out.append(a / b)
    return out


def claim_matches(claim: float, ndecimals: int,
                  numbers: List[float]) -> bool:
    tol = 10.0 ** (-ndecimals)
    return any(abs(n - claim) <= tol for n in numbers)


def find_claims(path: str) -> List[Tuple[int, str, float, int]]:
    """(line_no, line, claim_value, n_decimals) for each N.Nx."""
    claims = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            if "target" in line.lower():
                continue
            for m in CLAIM_RE.finditer(line):
                txt = m.group(1)
                claims.append((i, line.rstrip(), float(txt),
                               len(txt.split(".")[1])))
    return claims


def check_perf_claims(repo: str) -> List[Tuple[str, int, str]]:
    artifact_path = os.path.join(repo, ARTIFACT)
    with open(artifact_path) as f:
        detail = json.load(f)
    numbers = measured_numbers(detail)
    errors = []
    for doc in DOC_FILES:
        path = os.path.join(repo, doc)
        if not os.path.exists(path):
            continue
        for line_no, line, claim, nd in find_claims(path):
            if not claim_matches(claim, nd, numbers):
                errors.append((doc, line_no,
                               f"claim '{claim}x' has no measured "
                               f"counterpart in {ARTIFACT} "
                               f"(line: {line.strip()[:100]})"))
    return errors


# ---------------------------------------------------------------------------
# stale metric names
# ---------------------------------------------------------------------------

def _package_sources(repo: str) -> Iterable[str]:
    for root, dirs, files in os.walk(os.path.join(repo, PACKAGE_DIR)):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for fname in files:
            if fname.endswith(".py"):
                with open(os.path.join(root, fname),
                          encoding="utf-8", errors="replace") as f:
                    yield f.read()


def registered_metric_patterns(repo: str, sources=None
                               ) -> List[re.Pattern]:
    """Compile every metric-name literal under the package into a
    matcher; ``{...}`` f-string holes become wildcards."""
    patterns = set()
    for src in (sources if sources is not None
                else _package_sources(repo)):
        for m in SRC_METRIC_RE.finditer(src):
            patterns.add(m.group(1))
    out = []
    for p in sorted(patterns):
        rx = re.escape(p).replace(r"\{", "{").replace(r"\}", "}")
        rx = re.sub(r"\{[^{}]*\}", r"[a-zA-Z0-9_/.-]+", rx)
        out.append(re.compile(rx + r"\Z"))
    return out


def find_doc_metric_names(path: str) -> List[Tuple[int, str]]:
    names = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            for m in DOC_METRIC_RE.finditer(line):
                names.append((i, m.group(1)))
    return names


def check_metric_names_raw(repo: str, sources=None
                           ) -> List[Tuple[str, int, str]]:
    patterns = registered_metric_patterns(repo, sources)
    errors = []
    for doc in DOC_FILES:
        path = os.path.join(repo, doc)
        if not os.path.exists(path):
            continue
        for line_no, name in find_doc_metric_names(path):
            if not any(p.match(name) for p in patterns):
                errors.append((doc, line_no,
                               f"metric `{name}` is cited in the "
                               f"docs but registered nowhere under "
                               f"{PACKAGE_DIR}/ — stale name?"))
    return errors


# ---------------------------------------------------------------------------
# stale chaos-site names
# ---------------------------------------------------------------------------

def find_doc_site_names(path: str) -> List[Tuple[int, str]]:
    """Backticked dotted tokens inside any section whose heading
    mentions fault injection / chaos (scoped: a dotted token
    elsewhere in the docs — `np.ndarray`, module paths — is not a
    site citation). Fenced code blocks are skipped entirely: a shell
    comment's leading '#' is not a markdown heading and must not
    toggle the section scope."""
    names = []
    in_section = False
    in_fence = False
    with open(path) as f:
        for i, line in enumerate(f, 1):
            if line.lstrip().startswith("```"):
                in_fence = not in_fence
                continue
            if in_fence:
                continue
            if re.match(r"#+\s", line):
                low = line.lower()
                in_section = ("fault injection" in low
                              or "chaos" in low)
                continue
            if not in_section:
                continue
            for m in DOC_SITE_RE.finditer(line):
                token = m.group(1)
                if token.rsplit(".", 1)[-1] in _SITE_EXT_SKIP:
                    continue
                names.append((i, token))
    return names


def registered_site_literals(repo: str, sources=None) -> set:
    literals = set()
    for src in (sources if sources is not None
                else _package_sources(repo)):
        for m in SRC_SITE_RE.finditer(src):
            literals.add(m.group(1))
    return literals


def check_site_names_raw(repo: str, sources=None
                         ) -> List[Tuple[str, int, str]]:
    literals = registered_site_literals(repo, sources)
    errors = []
    for doc in DOC_FILES:
        path = os.path.join(repo, doc)
        if not os.path.exists(path):
            continue
        for line_no, name in find_doc_site_names(path):
            if name not in literals:
                errors.append((doc, line_no,
                               f"chaos site `{name}` is cited in "
                               f"the docs but exists as a string "
                               f"literal nowhere under "
                               f"{PACKAGE_DIR}/ — stale site name?"))
    return errors


# ---------------------------------------------------------------------------
# legacy string API (the check_perf_claims.py shim contract)
# ---------------------------------------------------------------------------

def _fmt(errors: List[Tuple[str, int, str]]) -> List[str]:
    return [f"{doc}:{line}: {msg}" for doc, line, msg in errors]


def check(repo: str) -> List[str]:
    """All three sub-checks, as ``DOC:LINE: message`` strings."""
    errors = check_perf_claims(repo)
    errors.extend(check_metric_names_raw(repo))
    errors.extend(check_site_names_raw(repo))
    return _fmt(errors)


def check_metric_names(repo: str) -> List[str]:
    return _fmt(check_metric_names_raw(repo))


def check_site_names(repo: str) -> List[str]:
    return _fmt(check_site_names_raw(repo))


# ---------------------------------------------------------------------------
# the rule
# ---------------------------------------------------------------------------

class LiteralDriftRule(Rule):
    id = "GL005"
    title = "literal-drift"
    rationale = ("doc perf claims, metric names and chaos sites "
                 "must keep matching code and bench artifacts")
    scope = "repo"

    def repo_triggered(self, relpath: str) -> bool:
        return (relpath in DOC_FILES or relpath == ARTIFACT
                or (relpath.startswith(PACKAGE_DIR + "/")
                    and relpath.endswith(".py")))

    def check_repo(self, ctx: RepoContext) -> Iterable[Finding]:
        errors: List[Tuple[str, int, str]] = []
        if os.path.exists(os.path.join(ctx.repo, ARTIFACT)):
            errors.extend(check_perf_claims(ctx.repo))
        # one package-source pass feeds both literal scans (the
        # legacy wrappers below still read independently)
        sources = list(_package_sources(ctx.repo))
        errors.extend(check_metric_names_raw(ctx.repo, sources))
        errors.extend(check_site_names_raw(ctx.repo, sources))
        return [Finding(rule=self.id, path=doc, line=line,
                        message=msg)
                for doc, line, msg in errors]
