"""GL003 — donation audit.

``donate_argnums`` hands a buffer to XLA for in-place reuse: after
the call, the Python name still points at an invalidated array, and
touching it raises (on real backends) or silently reads garbage
through a stale host copy. The repo's executors donate params /
state / opt-state on every train step, so the fit loops MUST follow
the ``x = step(x, ...)`` rebinding idiom; this rule flags any read
of a donated name after the donating call, in the same scope,
before the name is rebound.

Analysis is per lexical scope: a callable is "donating" when the
scope can see its ``donate_argnums`` — a decorated local ``def``, or
a ``name = jax.jit(f, donate_argnums=...)`` binding (resolved
through ``functools.partial`` / aliases). Reads inside conditional
branches count (the branch MAY execute); a rebind only clears the
poison when it is unconditional at the same statement level.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from tools.graftlint.core import Finding, ParsedModule
from tools.graftlint import jitscope
from tools.graftlint.rules.base import Rule


def _stmt_lists(node: ast.AST):
    """Yield every list-of-statements field of a compound node."""
    for field in ("body", "orelse", "finalbody"):
        lst = getattr(node, field, None)
        if isinstance(lst, list) and lst and isinstance(
                lst[0], ast.stmt):
            yield lst
    for h in getattr(node, "handlers", []) or []:
        yield h.body


def _loads(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _direct_stores(stmt: ast.stmt) -> Set[str]:
    """Names UNCONDITIONALLY rebound by this statement (assignment
    targets at its own level — not inside a nested if/for body)."""
    out: Set[str] = set()
    if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        targets = (stmt.targets if isinstance(stmt, ast.Assign)
                   else [stmt.target])
        for t in targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    out.add(n.id)
    return out


class DonationAuditRule(Rule):
    id = "GL003"
    title = "donation-audit"
    rationale = ("a buffer read after being donated to a jitted call "
                 "is invalid memory")
    scope = "file"

    def check(self, module: ParsedModule) -> Iterable[Finding]:
        info = module.jit_info
        donors: Dict[Tuple[ast.AST, str], jitscope.JitSite] = {}
        for site in info.sites:
            if site.bound_name and site.donate_argnums:
                donors[(site.scope, site.bound_name)] = site
        if not donors:
            return []
        out: List[Finding] = []
        scopes = {s for (s, _n) in donors}
        seen = set()
        for scope in scopes:
            for fn in self._functions_under(info, scope):
                if fn in seen:
                    continue            # reachable from two donor
                seen.add(fn)            # scopes: scan once
                out.extend(self._scan_function(
                    module, info, donors, fn))
        return out

    @staticmethod
    def _functions_under(info, scope) -> Iterable[ast.AST]:
        """Function bodies that can call a name bound in ``scope``:
        the scope itself (if a function/module) plus every function
        nested below it."""
        if isinstance(scope, jitscope.FunctionNode + (ast.Module,)):
            yield scope
        for node in ast.walk(scope):
            if node is not scope and isinstance(
                    node, jitscope.FunctionNode):
                yield node

    def _scan_function(self, module, info, donors, fn
                       ) -> List[Finding]:
        """Linear may-use scan over ``fn``'s statements."""
        out: List[Finding] = []
        poisoned: Dict[str, Tuple[str, int]] = {}  # name -> (callee, line)
        reported: Set[Tuple[int, str]] = set()

        def donating_site(call: ast.Call):
            if not isinstance(call.func, ast.Name):
                return None
            scope = info.enclosing_scope(call)
            while scope is not None:
                if (scope, call.func.id) in donors:
                    return donors[(scope, call.func.id)]
                if scope is info.tree:
                    return None
                scope = info.enclosing_scope(scope)
            return None

        def report(name: str, line: int) -> None:
            # NOTE: the donating call's line number must stay OUT of
            # the message — the message is part of the baseline key,
            # which is line-independent by contract (core.py)
            callee, _dline = poisoned.pop(name)    # report once
            if (line, name) in reported:     # loop bodies are walked
                return                       # twice — dedup sites
            reported.add((line, name))
            out.append(Finding(
                rule=self.id, path=module.relpath, line=line,
                symbol=getattr(fn, "name", "<module>"),
                message=(
                    f"'{name}' used after being donated to "
                    f"'{callee}' — the buffer was handed to XLA; "
                    "rebind the result (x = step(x, ...)) or drop "
                    "donate_argnums")))

        def process_compound(stmt, nested) -> None:
            # compound statement: check only its HEADER
            # (test/iter/with-items) here, then recurse —
            # body-level donations and uses must be seen in
            # their real order
            inner: Set[str] = set()
            for lst in nested:
                for s in lst:
                    inner |= _loads(s)
            header = _loads(stmt) - inner
            for name in sorted(header & set(poisoned)):
                report(name, stmt.lineno)
            for name in _direct_stores(stmt):
                poisoned.pop(name, None)
            for lst in nested:
                walk_stmts(lst)

        def walk_stmts(stmts: List[ast.stmt]) -> None:
            for stmt in stmts:
                nested = list(_stmt_lists(stmt))
                if nested and not isinstance(
                        stmt, jitscope.FunctionNode):
                    process_compound(stmt, nested)
                    if isinstance(stmt, (ast.For, ast.AsyncFor,
                                         ast.While)):
                        # symbolic SECOND iteration: a name donated
                        # in the body and not rebound by loop top is
                        # read as invalid memory next time around
                        # (`for b in xs: outs.append(step(params, b))`)
                        process_compound(stmt, nested)
                    continue
                if isinstance(stmt, jitscope.FunctionNode):
                    continue           # nested defs scan separately
                # simple statement: uses first (the donating
                # statement's own arg reads are not uses-after).
                # An AugAssign target reads the buffer before
                # writing (x += g desugars to x = x + g) even though
                # its Name ctx is Store — count it as a use.
                uses = _loads(stmt) & set(poisoned)
                if isinstance(stmt, ast.AugAssign) and isinstance(
                        stmt.target, ast.Name) and \
                        stmt.target.id in poisoned:
                    uses.add(stmt.target.id)
                for name in sorted(uses):
                    report(name, stmt.lineno)
                stores = _direct_stores(stmt)
                for name in stores:
                    poisoned.pop(name, None)
                for call in [n for n in ast.walk(stmt)
                             if isinstance(n, ast.Call)]:
                    site = donating_site(call)
                    if site is None:
                        continue
                    for i in site.donate_argnums:
                        if i < len(call.args) and isinstance(
                                call.args[i], ast.Name):
                            name = call.args[i].id
                            if name not in stores:
                                poisoned[name] = (call.func.id,
                                                  call.lineno)

        body = getattr(fn, "body", None)
        if isinstance(body, list):
            walk_stmts(body)
        return out
