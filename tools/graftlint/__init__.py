"""graftlint — repo-specific static analysis for the jax_graft tree.

Five AST-level checkers enforce the invariants the threaded, jitted
production substrate depends on, BEFORE execution (the runtime
watchdogs in ``observability/`` catch the same bug classes only after
they cost a compile or a deadlock):

========  ==================================================
GL001     jit-purity: no host side effects inside traced code
GL002     recompile-hazard: shape/f-string static args, traced
          branches, jit-in-loop, raw-shape cache keys
GL003     donation-audit: no use of a buffer after it was
          donated to a jitted call
GL004     lock-discipline: consistent acquisition order and
          no shared attribute mutated both with and without
          its lock in thread-spawning classes
GL005     literal-drift: doc perf claims / metric names /
          chaos sites must match code and bench artifacts
========  ==================================================

Run ``python -m tools.graftlint [paths]``; suppress one finding with
``# graftlint: disable=GL001`` (same line or the line above), a whole
file with ``# graftlint: disable-file=GL001``. Pre-existing findings
live in ``tools/graftlint/baseline.json`` (the ratchet): they do not
fail the run, but any NEW finding does.
"""

from tools.graftlint.core import (Baseline, Finding, LintReport,
                                  ParsedModule, RepoContext,
                                  format_json, format_text,
                                  format_stats, run_lint)
from tools.graftlint.rules import ALL_RULES

__all__ = ["ALL_RULES", "Baseline", "Finding", "LintReport",
           "ParsedModule", "RepoContext", "format_json",
           "format_text", "format_stats", "run_lint"]
