"""graftlint — repo-specific static analysis for the jax_graft tree.

Eleven checkers enforce the invariants the threaded, jitted
production substrate depends on, BEFORE execution (the runtime
watchdogs in ``observability/`` catch the same bug classes only after
they cost a compile or a deadlock):

========  ==================================================
GL001     jit-purity: no host side effects inside traced code
GL002     recompile-hazard: shape/f-string static args, traced
          branches, jit-in-loop, raw-shape cache keys
GL003     donation-audit: no use of a buffer after it was
          donated to a jitted call
GL004     lock-discipline: consistent acquisition order and
          no shared attribute mutated both with and without
          its lock in thread-spawning classes
GL005     literal-drift: doc perf claims / metric names /
          chaos sites must match code and bench artifacts
GL006     metrics-hygiene: no per-request identity in metric
          labels; instruments created once, not in hot loops
GL007     thread-lifecycle: server threads joinable and
          joined; one fresh stop event per generation
GL008     deadline-discipline: no timeout-less blocking call
          reachable from an HTTP handler or worker loop
GL009     resource-pairing: per-instance gauges unregistered,
          listeners server_close()d, fds released on all exits
GL010     serving-error-contract: 429/503 errors carry
          retry_after_s on admission paths; handler status
          codes match the README failure matrix
GL011     chaos-site-coverage: SITES/SITE_KINDS vs threaded
          call-site literals vs the README table, three-way
========  ==================================================

GL001-GL006 are per-file AST walks; GL007-GL011 (ISSUE 14) run over
the project-wide call graph in ``callgraph.py`` — per-function
summaries resolved through ``self``-dispatch, inferred attribute and
local types, annotated returns, and thread-target/callback
references.

Run ``python -m tools.graftlint [paths]``; suppress one finding with
``# graftlint: disable=GL001`` (same line or the line above), a whole
file with ``# graftlint: disable-file=GL001``. Pre-existing findings
live in ``tools/graftlint/baseline.json`` (the ratchet): they do not
fail the run, but any NEW finding does. ``--jobs N`` parallelizes
the per-file pass; the content-hash cache (``.graftlint_cache.json``)
keeps warm full-tree runs fast.
"""

from tools.graftlint.core import (Baseline, Finding, LintReport,
                                  ParsedModule, RepoContext,
                                  format_json, format_text,
                                  format_stats, run_lint)
from tools.graftlint.rules import ALL_RULES

__all__ = ["ALL_RULES", "Baseline", "Finding", "LintReport",
           "ParsedModule", "RepoContext", "format_json",
           "format_text", "format_stats", "run_lint"]
