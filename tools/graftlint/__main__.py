"""CLI: ``python -m tools.graftlint [paths] [options]``.

Exit codes: 0 = no new findings (baselined/suppressed ones are
reported but do not fail), 1 = new findings, 2 = bad invocation.
"""

from __future__ import annotations

import argparse
import os
import sys


def _ensure_repo_on_path() -> None:
    here = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    if here not in sys.path:
        sys.path.insert(0, here)


_ensure_repo_on_path()

from tools.graftlint.core import (Baseline, DEFAULT_BASELINE,  # noqa: E402
                                  PACKAGE_DIR, format_json,
                                  format_stats, format_text,
                                  run_lint)
from tools.graftlint.rules import ALL_RULES  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="repo-specific static analysis: "
                    + "; ".join(f"{rid} {cls.title}"
                                for rid, cls in sorted(
                                    ALL_RULES.items())))
    ap.add_argument("paths", nargs="*", default=[PACKAGE_DIR],
                    help=f"files/directories to lint "
                         f"(default: {PACKAGE_DIR}/)")
    ap.add_argument("--repo", default=None,
                    help="repo root (default: the directory holding "
                         "tools/)")
    ap.add_argument("--rule", action="append", default=[],
                    help="run only these rules (comma-separated, "
                         "repeatable), e.g. --rule GL001,GL004")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text")
    ap.add_argument("--baseline", default=None,
                    help=f"ratchet baseline file (default: "
                         f"{DEFAULT_BASELINE} when present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: every finding is new")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline to the current "
                         "findings (keeps recorded justifications "
                         "for surviving entries) and exit 0")
    ap.add_argument("--stats", action="store_true",
                    help="print the per-rule ratchet report "
                         "(current vs baseline allowance)")
    ap.add_argument("--changed-only", action="store_true",
                    help="lint only files changed vs git HEAD "
                         "(plus untracked); deleted/renamed paths "
                         "are skipped, and triggered repo-scope "
                         "rules still analyze the full tree")
    ap.add_argument("--jobs", type=int, default=1, metavar="N",
                    help="run the per-file pass on N worker "
                         "processes (default 1)")
    ap.add_argument("--cache", default=None, metavar="PATH",
                    help="on-disk result cache for the per-file "
                         "pass, keyed by content hash (default: "
                         ".graftlint_cache.json at the repo root)")
    ap.add_argument("--no-cache", action="store_true",
                    help="disable the on-disk cache")
    args = ap.parse_args(argv)

    repo = os.path.abspath(args.repo or os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    rules = [r.strip() for spec in args.rule
             for r in spec.split(",") if r.strip()] or None

    baseline_path = args.baseline or os.path.join(
        repo, DEFAULT_BASELINE)
    baseline = None
    if not args.no_baseline and os.path.exists(baseline_path):
        try:
            baseline = Baseline.load(baseline_path)
        except (OSError, ValueError, KeyError) as e:
            print(f"graftlint: cannot read baseline "
                  f"{baseline_path}: {e}", file=sys.stderr)
            return 2

    cache_path = None
    if not args.no_cache:
        cache_path = args.cache or os.path.join(
            repo, ".graftlint_cache.json")

    try:
        report = run_lint(repo, paths=args.paths, rules=rules,
                          baseline=baseline,
                          changed_only=args.changed_only,
                          jobs=max(1, args.jobs),
                          cache_path=cache_path)
    except ValueError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        new_base = Baseline.from_findings(
            report.new + report.baselined, previous=baseline)
        new_base.save(baseline_path)
        print(f"graftlint: baseline rewritten to {baseline_path} "
              f"({len(report.new) + len(report.baselined)} "
              "entries); review the diff and add a 'why' to "
              "anything kept deliberately")
        return 0

    if args.stats:
        print(format_stats(report, baseline))
        return 0 if report.ok else 1

    out = (format_json(report) if args.format == "json"
           else format_text(report))
    print(out)
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
