"""Project-wide call graph with per-function summaries — the
interprocedural engine under GL007-GL011 (ISSUE 14).

The jitscope module answers "which bodies are traced" lexically and
per-module; this module answers "who calls whom" across the whole
analyzed set, precisely enough to walk a request path from an HTTP
handler into a backend three modules away:

- every function/method gets a :class:`FunctionInfo` keyed by a
  dotted qname (``pkg.mod.Class.method``, nested scopes included);
- classes get a :class:`ClassInfo` with their base classes resolved
  through import aliases, an MRO limited to the analyzed set, and
  **attribute types** inferred from ``self.x = SomeClass(...)``
  assignments anywhere in the class;
- call edges resolve bare names, ``self.method()`` (through the MRO
  *and* down to subclass overrides — ``self._loop()`` in a base
  worker reaches every subclass loop), ``self.attr.method()`` and
  ``local.method()`` through the inferred types, dotted module calls
  through import aliases, and **tuple-unpacked return annotations**
  (``sched, v = self.scheduler_for(...)`` types ``sched`` from the
  ``-> Tuple[BatchScheduler, int]`` annotation);
- a resolvable function passed as a *bare argument* (``Thread(
  target=self._run)``, ``self._serve_request(server._handle_predict)``,
  ``fn=self.queue_depth``) becomes a **ref edge**: the referencing
  function is treated as a caller, which is exactly how thread
  targets and handler callbacks flow;
- per-function :class:`BlockingSite` summaries record the blocking
  primitives GL008 cares about (timeout-less ``queue.get`` /
  ``Event.wait`` / ``Condition.wait`` / ``lock.acquire`` / socket
  ``accept``/``recv`` / ``HTTPConnection`` without a timeout), and
  per-function raise/construct sites of the typed serving errors
  feed GL010.

Resolution stays purely lexical (no imports executed). Unresolvable
receivers produce *no* edge — the rules built on top are precise
along resolved paths and silent elsewhere, the polarity a CI gate
needs.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.graftlint import jitscope
from tools.graftlint.core import ParsedModule, RepoContext

FunctionNode = jitscope.FunctionNode

# blocking primitives whose zero-timeout forms GL008 flags
_HTTP_CONN = {"http.client.HTTPConnection", "HTTPConnection",
              "http.client.HTTPSConnection", "HTTPSConnection"}
_SERVING_ERRORS_MODULE = "deeplearning4j_tpu.serving.errors"


def _module_name(relpath: str) -> str:
    name = relpath[:-3] if relpath.endswith(".py") else relpath
    name = name.replace("/", ".")
    if name.endswith(".__init__"):
        name = name[: -len(".__init__")]
    return name


@dataclasses.dataclass
class BlockingSite:
    line: int
    primitive: str          # "queue.get", "Event/Condition.wait", ...
    detail: str             # the receiver text, for the message


@dataclasses.dataclass
class ErrorSite:
    line: int
    error: str              # class name, e.g. "ServerClosedError"
    raised: bool            # raise X(...) vs bare construction
    has_retry_after: bool


class FunctionInfo:
    def __init__(self, qname: str, node: ast.AST,
                 module: ParsedModule,
                 class_qname: Optional[str]):
        self.qname = qname
        self.node = node
        self.module = module
        self.class_qname = class_qname
        self.edges: Set[str] = set()          # callee qnames
        self.blocking: List[BlockingSite] = []
        self.errors: List[ErrorSite] = []

    @property
    def short(self) -> str:
        """``Class.method`` / ``func`` — the readable identity."""
        mod = _module_name(self.module.relpath)
        s = self.qname[len(mod) + 1:] if self.qname.startswith(
            mod + ".") else self.qname
        return s

    @property
    def name(self) -> str:
        return self.qname.rsplit(".", 1)[-1]


class ClassInfo:
    def __init__(self, qname: str, node: ast.ClassDef,
                 module: ParsedModule):
        self.qname = qname
        self.node = node
        self.module = module
        self.base_names: List[str] = []       # canonical, unresolved
        self.bases: List["ClassInfo"] = []    # resolved, in-set
        self.subclasses: List["ClassInfo"] = []
        self.methods: Dict[str, FunctionInfo] = {}
        self.attr_types: Dict[str, str] = {}  # self.x -> class qname
        self.calls_settimeout = False

    def mro(self) -> List["ClassInfo"]:
        out, seen, queue_ = [], set(), [self]
        while queue_:
            c = queue_.pop(0)
            if c.qname in seen:
                continue
            seen.add(c.qname)
            out.append(c)
            queue_.extend(c.bases)
        return out

    def find_method(self, name: str) -> Optional[FunctionInfo]:
        for c in self.mro():
            if name in c.methods:
                return c.methods[name]
        return None

    def all_subclasses(self) -> List["ClassInfo"]:
        out, queue_ = [], list(self.subclasses)
        while queue_:
            c = queue_.pop(0)
            out.append(c)
            queue_.extend(c.subclasses)
        return out

    def attr_type(self, attr: str) -> Optional[str]:
        for c in self.mro():
            if attr in c.attr_types:
                return c.attr_types[attr]
        return None


def _own_statements(fn: ast.AST) -> Iterable[ast.AST]:
    """Walk a function body without descending into nested
    def/lambda/class bodies (those are separate graph nodes)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, FunctionNode + (ast.Lambda,
                                            ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class CallGraph:
    """Build once per :class:`RepoContext`; shared by GL008/GL010
    (and anything else that needs reachability)."""

    def __init__(self, ctx: RepoContext):
        self.ctx = ctx
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        # per-module: local name -> class qname (defined or imported)
        self._mod_classnames: Dict[str, Dict[str, str]] = {}
        self._mod_settimeout: Dict[str, bool] = {}
        self._index()
        self._resolve_bases()
        self._infer_attr_types()
        self._build_edges()

    # ------------------------------------------------------------ index
    def _qualpath(self, module: ParsedModule, node: ast.AST) -> str:
        info = module.jit_info
        parts = []
        cur = node
        while cur is not None and not isinstance(cur, ast.Module):
            if isinstance(cur, FunctionNode + (ast.ClassDef,)):
                parts.append(cur.name)
            cur = info.parents.get(cur)
        return ".".join(reversed(parts))

    def _index(self) -> None:
        for module in self.ctx.modules:
            modname = _module_name(module.relpath)
            info = module.jit_info
            names: Dict[str, str] = {}
            settimeout = False
            for node in ast.walk(module.tree):
                if isinstance(node, ast.ClassDef):
                    q = f"{modname}.{self._qualpath(module, node)}"
                    self.classes[q] = ClassInfo(q, node, module)
                    if isinstance(info.parents.get(node), ast.Module):
                        names[node.name] = q
                elif isinstance(node, FunctionNode):
                    q = f"{modname}.{self._qualpath(module, node)}"
                    parent = info.parents.get(node)
                    cls_q = None
                    if isinstance(parent, ast.ClassDef):
                        cls_q = f"{modname}." + self._qualpath(
                            module, parent)
                    self.functions[q] = FunctionInfo(
                        q, node, module, cls_q)
                elif isinstance(node, ast.Attribute) and \
                        node.attr == "settimeout":
                    settimeout = True
            # imported classes resolve through the alias map lazily;
            # record module-level class names now
            self._mod_classnames[modname] = names
            self._mod_settimeout[modname] = settimeout
        for fn in self.functions.values():
            if fn.class_qname and fn.class_qname in self.classes:
                self.classes[fn.class_qname].methods.setdefault(
                    fn.name, fn)

    def _canon(self, module: ParsedModule, node: ast.AST) -> str:
        return module.jit_info.canon(node)

    def _class_by_canonical(self, modname: str,
                            canon: str) -> Optional[ClassInfo]:
        """A canonical dotted name -> in-set class: exact qname, a
        module-local name, or (for ``import x as y`` prefixes) the
        longest matching class qname."""
        if not canon:
            return None
        if canon in self.classes:
            return self.classes[canon]
        local = self._mod_classnames.get(modname, {})
        if canon in local:
            return self.classes.get(local[canon])
        return None

    def _resolve_bases(self) -> None:
        for cls in self.classes.values():
            modname = _module_name(cls.module.relpath)
            for base in cls.node.bases:
                canon = self._canon(cls.module, base)
                cls.base_names.append(canon)
                b = self._class_by_canonical(modname, canon)
                if b is not None:
                    cls.bases.append(b)
                    b.subclasses.append(cls)
            if self._mod_settimeout.get(modname) and any(
                    isinstance(n, ast.Attribute)
                    and n.attr == "settimeout"
                    for n in ast.walk(cls.node)):
                cls.calls_settimeout = True

    def _infer_attr_types(self) -> None:
        for cls in self.classes.values():
            modname = _module_name(cls.module.relpath)
            for node in ast.walk(cls.node):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    continue
                t = self._class_by_canonical(
                    modname, self._canon(cls.module,
                                         node.value.func))
                if t is None:
                    continue
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) and isinstance(
                            tgt.value, ast.Name) and \
                            tgt.value.id == "self":
                        cls.attr_types[tgt.attr] = t.qname

    # ------------------------------------------------- type inference
    def _annotation_types(self, module: ParsedModule,
                          ann: Optional[ast.AST]
                          ) -> List[Optional[str]]:
        """Class qnames named by a return annotation: ``X`` ->
        ``[X]``; ``Tuple[X, int]`` -> ``[X, None]``; ``Optional[X]``
        -> ``[X]``. Unknown -> []."""
        if ann is None:
            return []
        modname = _module_name(module.relpath)

        def one(node) -> Optional[str]:
            if isinstance(node, ast.Subscript):
                head = self._canon(module, node.value)
                if head.rsplit(".", 1)[-1] in ("Optional",):
                    return one(node.slice)
                return None
            c = self._class_by_canonical(
                modname, self._canon(module, node))
            return c.qname if c else None

        if isinstance(ann, ast.Subscript):
            head = self._canon(module, ann.value)
            tail = head.rsplit(".", 1)[-1]
            if tail in ("Tuple", "tuple"):
                elts = (ann.slice.elts
                        if isinstance(ann.slice, ast.Tuple) else [])
                return [one(e) for e in elts]
            if tail == "Optional":
                return [one(ann.slice)]
            return []
        t = one(ann)
        return [t] if t else []

    def _local_types(self, fn: FunctionInfo,
                     scopes: Dict[ast.AST, Dict[str, str]]
                     ) -> Dict[str, str]:
        """name -> class qname for this function's locals (ctor
        calls, ``x = self``, annotated-return unpacks), falling back
        to lexically enclosing function scopes (closures)."""
        module = fn.module
        modname = _module_name(module.relpath)
        out: Dict[str, str] = {}
        # closure fallback: nearest enclosing function's locals
        info = module.jit_info
        cur = info.parents.get(fn.node)
        while cur is not None:
            if cur in scopes:
                for k, v in scopes[cur].items():
                    out.setdefault(k, v)
            cur = info.parents.get(cur)
        for node in _own_statements(fn.node):
            if not isinstance(node, ast.Assign):
                continue
            val = node.value
            types: List[Optional[str]] = []
            if isinstance(val, ast.Name) and val.id == "self" and \
                    fn.class_qname:
                types = [fn.class_qname]
            elif isinstance(val, ast.Call):
                c = self._class_by_canonical(
                    modname, self._canon(module, val.func))
                if c is not None:
                    types = [c.qname]
                else:
                    callee = self._resolve_callable(fn, val.func,
                                                    out, scopes)
                    if callee and isinstance(callee.node,
                                             FunctionNode):
                        types = self._annotation_types(
                            callee.module, callee.node.returns)
            if not types:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and types[0]:
                    out[tgt.id] = types[0]
                elif isinstance(tgt, ast.Tuple):
                    for i, e in enumerate(tgt.elts):
                        if isinstance(e, ast.Name) and \
                                i < len(types) and types[i]:
                            out[e.id] = types[i]
        return out

    # ---------------------------------------------------- resolution
    def _resolve_callable(self, fn: FunctionInfo, func: ast.AST,
                          local_types: Dict[str, str],
                          scopes) -> Optional[FunctionInfo]:
        """The single call/ref resolver; returns the PRIMARY target
        (subclass overrides are added by the edge builder)."""
        module = fn.module
        modname = _module_name(module.relpath)
        # self.m()
        if isinstance(func, ast.Attribute) and isinstance(
                func.value, ast.Name):
            recv = func.value.id
            if recv == "self" and fn.class_qname in self.classes:
                return self.classes[fn.class_qname].find_method(
                    func.attr)
            t = local_types.get(recv)
            if t and t in self.classes:
                return self.classes[t].find_method(func.attr)
        # self.attr.m()
        if isinstance(func, ast.Attribute) and isinstance(
                func.value, ast.Attribute) and isinstance(
                func.value.value, ast.Name) and \
                func.value.value.id == "self" and \
                fn.class_qname in self.classes:
            t = self.classes[fn.class_qname].attr_type(
                func.value.attr)
            if t and t in self.classes:
                return self.classes[t].find_method(func.attr)
        # dotted: mod.f / mod.Class.m / Class.m / imported f
        canon = self._canon(module, func)
        if canon:
            if canon in self.functions:
                return self.functions[canon]
            # imported bare name / alias: canonical already dotted
            if "." not in canon:
                q = f"{modname}.{canon}"
                if q in self.functions:
                    return self.functions[q]
                # nested function in an enclosing scope
                target = module.jit_info.resolve_callable(
                    module.jit_info.enclosing_scope(func), canon)
                if target is not None and isinstance(target,
                                                     FunctionNode):
                    q2 = (f"{modname}."
                          f"{self._qualpath(module, target)}")
                    return self.functions.get(q2)
            else:
                head, _, meth = canon.rpartition(".")
                c = self._class_by_canonical(modname, head)
                if c is not None:
                    return c.find_method(meth)
        return None

    def _targets_with_overrides(self, fn: FunctionInfo,
                                target: FunctionInfo
                                ) -> List[FunctionInfo]:
        out = [target]
        if target.class_qname and target.class_qname in self.classes:
            cls = self.classes[target.class_qname]
            # dynamic dispatch: a subclass override is a possible
            # callee whenever the static target is a method
            for sub in cls.all_subclasses():
                m = sub.methods.get(target.name)
                if m is not None:
                    out.append(m)
        return out

    # --------------------------------------------------- edge builder
    @staticmethod
    def _kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
        for k in call.keywords:
            if k.arg == name:
                return k.value
        return None

    def _timeoutless(self, call: ast.Call) -> bool:
        """True when this call passes NO deadline: no positional
        args that could be one, and no ``timeout=`` kwarg (or a
        literal ``timeout=None``)."""
        to = self._kwarg(call, "timeout")
        if to is not None:
            return isinstance(to, ast.Constant) and to.value is None
        # any positional argument may be the timeout (queue.get's
        # first positional is `block`, but passing block without
        # timeout is rare enough to stay silent on)
        return not call.args

    def _blocking_site(self, fn: FunctionInfo, call: ast.Call,
                       resolved: Optional[FunctionInfo]
                       ) -> Optional[BlockingSite]:
        func = call.func
        # HTTPConnection(...) constructor without a timeout: its
        # getresponse()/connect() then block forever (dotted or
        # bare-name form)
        canon = self._canon(fn.module, func)
        if canon in _HTTP_CONN and \
                self._kwarg(call, "timeout") is None:
            return BlockingSite(
                call.lineno, f"{canon.rsplit('.', 1)[-1]}(...)", "")
        if not isinstance(func, ast.Attribute):
            return None
        if resolved is not None:
            return None          # analyzed callee: followed instead
        recv = ast.unparse(func.value) if hasattr(ast, "unparse") \
            else ""
        name = func.attr
        if name == "get" and self._timeoutless(call) and \
                not call.args:
            # zero-arg .get(): a queue (dict.get needs a key)
            return BlockingSite(call.lineno, "queue.get", recv)
        if name == "wait" and self._timeoutless(call):
            return BlockingSite(call.lineno, "wait", recv)
        if name == "acquire" and not call.args and \
                self._kwarg(call, "timeout") is None and \
                "lock" in recv.lower():
            return BlockingSite(call.lineno, "lock.acquire", recv)
        if name == "getresponse" and not call.args:
            # only blocking when the connection has no timeout; the
            # constructor check above owns that case
            return None
        if name in ("accept", "recv", "recvfrom"):
            cls = self.classes.get(fn.class_qname or "")
            has_settimeout = (cls.calls_settimeout if cls else False) \
                or self._mod_settimeout.get(
                    _module_name(fn.module.relpath), False)
            if not has_settimeout:
                return BlockingSite(call.lineno, f"socket.{name}",
                                    recv)
        if name == "communicate" and \
                self._kwarg(call, "timeout") is None and \
                not call.args:
            return BlockingSite(call.lineno,
                                "subprocess.communicate", recv)
        return None

    def _error_site(self, fn: FunctionInfo,
                    call: ast.Call, raised: bool
                    ) -> Optional[ErrorSite]:
        canon = self._canon(fn.module, call.func)
        name = canon.rsplit(".", 1)[-1]
        if not name.endswith("Error"):
            return None
        return ErrorSite(call.lineno, name, raised,
                         self._kwarg(call, "retry_after_s")
                         is not None)

    def _build_edges(self) -> None:
        # per-function local-type scopes, for closure fallback
        scopes: Dict[ast.AST, Dict[str, str]] = {}
        ordered = sorted(self.functions.values(),
                         key=lambda f: f.qname.count("."))
        for fn in ordered:
            scopes[fn.node] = self._local_types(fn, scopes)
        for fn in self.functions.values():
            local_types = scopes[fn.node]
            raised_calls: Set[ast.AST] = set()
            for node in _own_statements(fn.node):
                if isinstance(node, ast.Raise) and isinstance(
                        node.exc, ast.Call):
                    raised_calls.add(node.exc)
            for node in _own_statements(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                resolved = self._resolve_callable(
                    fn, node.func, local_types, scopes)
                if resolved is not None:
                    for t in self._targets_with_overrides(fn,
                                                          resolved):
                        fn.edges.add(t.qname)
                site = self._blocking_site(fn, node, resolved)
                if site is not None:
                    fn.blocking.append(site)
                err = self._error_site(fn, node,
                                       node in raised_calls)
                if err is not None:
                    fn.errors.append(err)
                # ref edges: a resolvable function passed as a bare
                # argument (thread target, handler callback, gauge fn)
                for arg in list(node.args) + [
                        k.value for k in node.keywords]:
                    if isinstance(arg, (ast.Name, ast.Attribute)):
                        t = self._resolve_callable(
                            fn, arg, local_types, scopes)
                        if t is not None:
                            for tt in self._targets_with_overrides(
                                    fn, t):
                                fn.edges.add(tt.qname)

    # ------------------------------------------------------- queries
    def handler_roots(self) -> List[FunctionInfo]:
        """HTTP entry points: ``do_*`` methods plus the
        ``_handle_*``/``handle_*`` convention the serving stack
        uses."""
        out = []
        for fn in self.functions.values():
            n = fn.name
            if n.startswith("do_") and n[3:].isupper():
                out.append(fn)
            elif (n.startswith("_handle_") or n.startswith("handle_")) \
                    and fn.class_qname:
                out.append(fn)
        return sorted(out, key=lambda f: f.qname)

    def worker_roots(self) -> List[FunctionInfo]:
        """Thread-target functions: anything passed as ``target=`` to
        ``threading.Thread`` (resolved), i.e. code that runs on a
        spawned thread."""
        out: Set[str] = set()
        scopes: Dict[ast.AST, Dict[str, str]] = {
            fn.node: self._local_types(fn, {})
            for fn in self.functions.values()}
        for fn in self.functions.values():
            for node in _own_statements(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                canon = self._canon(fn.module, node.func)
                if canon.rsplit(".", 1)[-1] != "Thread":
                    continue
                tgt = self._kwarg(node, "target")
                if tgt is None:
                    continue
                t = self._resolve_callable(fn, tgt,
                                           scopes.get(fn.node, {}),
                                           scopes)
                if t is not None:
                    for tt in self._targets_with_overrides(fn, t):
                        out.add(tt.qname)
        return sorted((self.functions[q] for q in out
                       if q in self.functions),
                      key=lambda f: f.qname)

    def reachable_from(self, roots: Sequence[FunctionInfo]
                       ) -> Dict[str, str]:
        """qname -> the (sorted-first) root qname that reaches it."""
        owner: Dict[str, str] = {}
        for root in roots:
            stack = [root.qname]
            while stack:
                q = stack.pop()
                if q in owner:
                    continue
                owner[q] = root.qname
                fn = self.functions.get(q)
                if fn is None:
                    continue
                stack.extend(sorted(fn.edges - set(owner)))
        return owner


_GRAPH_ATTR = "_graftlint_callgraph"


def get_graph(ctx: RepoContext) -> CallGraph:
    """One graph per RepoContext — GL008 and GL010 share it."""
    g = getattr(ctx, _GRAPH_ATTR, None)
    if g is None:
        g = CallGraph(ctx)
        setattr(ctx, _GRAPH_ATTR, g)
    return g
