"""graftlint core: findings, suppressions, the ratchet baseline, the
runner, and the output formats.

Design points that matter to rule authors:

- A :class:`Finding`'s baseline identity (``key``) deliberately
  EXCLUDES the line number: an unrelated edit above a pre-existing
  finding must not turn it "new" and break CI. Identity is
  ``rule|path|symbol|message``; duplicates within one key are
  ratcheted by count (two pre-existing, three now -> one new).
- Suppression comments are parsed from the RAW text of whichever file
  a finding points at, so ``# graftlint: disable=GL001`` works in
  Python and ``<!-- graftlint: disable=GL005 -->`` works in the
  markdown GL005 lints. An inline marker suppresses its own line; a
  marker on a line of its own also suppresses the next line;
  ``disable-file=`` suppresses the whole file. ``disable=all`` is
  accepted.
- File-scope rules run per parsed module; repo-scope rules (GL004's
  cross-file lock graph, GL005's doc lint) run once over a
  :class:`RepoContext`.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import subprocess
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

PACKAGE_DIR = "deeplearning4j_tpu"
DEFAULT_BASELINE = os.path.join("tools", "graftlint", "baseline.json")

_SUPPRESS_RE = re.compile(
    r"graftlint:\s*disable(?P<file>-file)?\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_,\s]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str           # "GL001"
    path: str           # repo-relative, posix separators
    line: int           # 1-based; 0 = whole file
    message: str
    symbol: str = ""    # enclosing function/class, for stable identity

    @property
    def key(self) -> str:
        """Baseline identity — no line number (see module doc)."""
        return "|".join((self.rule, self.path, self.symbol,
                         self.message))

    def render(self) -> str:
        where = f"{self.path}:{self.line}" if self.line else self.path
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{where}: {self.rule}{sym}: {self.message}"


class Suppressions:
    """Per-file suppression map parsed from raw text lines."""

    def __init__(self, text: str):
        self.file_rules: set = set()
        self.line_rules: Dict[int, set] = {}
        for i, line in enumerate(text.splitlines(), 1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = {r.strip().upper()
                     for r in m.group("rules").split(",") if r.strip()}
            if m.group("file"):
                self.file_rules |= rules
                continue
            self.line_rules.setdefault(i, set()).update(rules)
            # a marker on a comment-only line guards the line below
            stripped = line.strip()
            if stripped.startswith(("#", "<!--", "//")):
                self.line_rules.setdefault(i + 1, set()).update(rules)

    def suppressed(self, rule: str, line: int) -> bool:
        hits = self.file_rules | self.line_rules.get(line, set())
        return rule in hits or "ALL" in hits


class ParsedModule:
    """One analyzed Python file: source, AST, repo-relative path."""

    def __init__(self, path: str, repo: str):
        self.path = os.path.abspath(path)
        self.relpath = os.path.relpath(self.path, repo).replace(
            os.sep, "/")
        with open(self.path, encoding="utf-8", errors="replace") as f:
            self.source = f.read()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[Finding] = None
        self._jit_info = None
        try:
            self.tree = ast.parse(self.source, filename=self.relpath)
        except SyntaxError as e:
            self.parse_error = Finding(
                rule="GL000", path=self.relpath, line=e.lineno or 0,
                message=f"file does not parse: {e.msg}")

    @property
    def jit_info(self):
        """Shared :class:`jitscope.ModuleJitInfo` — built once per
        module per run, not once per rule (GL001-GL004 all need
        it)."""
        if self._jit_info is None:
            from tools.graftlint import jitscope
            self._jit_info = jitscope.ModuleJitInfo(self.tree)
        return self._jit_info


class RepoContext:
    """What repo-scope rules see: the repo root plus every module the
    current invocation parsed."""

    def __init__(self, repo: str, modules: Sequence[ParsedModule]):
        self.repo = repo
        self.modules = list(modules)


# ---------------------------------------------------------------------------
# baseline (the ratchet)
# ---------------------------------------------------------------------------

class Baseline:
    """``{key: {count, why}}``. Findings matching a key are absorbed
    up to ``count``; everything beyond — and every unknown key — is
    NEW and fails the run. ``why`` records the one-line justification
    for keeping a finding instead of fixing it."""

    def __init__(self, entries: Optional[Dict[str, dict]] = None):
        self.entries: Dict[str, dict] = dict(entries or {})

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        entries = {}
        for e in data.get("entries", []):
            entries[e["key"]] = {"count": int(e.get("count", 1)),
                                 "why": e.get("why", "")}
        return cls(entries)

    def save(self, path: str) -> None:
        data = {"version": 1,
                "entries": [{"key": k,
                             "count": v["count"],
                             **({"why": v["why"]} if v.get("why")
                                else {})}
                            for k, v in sorted(self.entries.items())]}
        with open(path, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=1, sort_keys=False)
            f.write("\n")

    def split(self, findings: Sequence[Finding]
              ) -> Tuple[List[Finding], List[Finding]]:
        """-> (new, baselined)."""
        budget = {k: v["count"] for k, v in self.entries.items()}
        new, old = [], []
        for f in findings:
            if budget.get(f.key, 0) > 0:
                budget[f.key] -= 1
                old.append(f)
            else:
                new.append(f)
        return new, old

    @classmethod
    def from_findings(cls, findings: Sequence[Finding],
                      previous: Optional["Baseline"] = None
                      ) -> "Baseline":
        """Rewrite the baseline to the current findings, keeping any
        ``why`` already recorded for surviving keys."""
        entries: Dict[str, dict] = {}
        for f in findings:
            e = entries.setdefault(f.key, {"count": 0, "why": ""})
            e["count"] += 1
        if previous is not None:
            for k, e in entries.items():
                prev = previous.entries.get(k)
                if prev and prev.get("why"):
                    e["why"] = prev["why"]
        return cls(entries)


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class LintReport:
    new: List[Finding]
    baselined: List[Finding]
    suppressed: int
    rules_run: List[str]
    files_checked: int
    # per-rule wall time (seconds) and cache traffic, for --stats
    timings: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    cache_hits: int = 0
    cache_misses: int = 0

    @property
    def ok(self) -> bool:
        return not self.new

    def per_rule(self) -> Dict[str, Dict[str, int]]:
        out: Dict[str, Dict[str, int]] = {}
        for rid in self.rules_run:
            out[rid] = {"new": 0, "baselined": 0}
        for f in self.new:
            out.setdefault(f.rule, {"new": 0, "baselined": 0})
            out[f.rule]["new"] += 1
        for f in self.baselined:
            out.setdefault(f.rule, {"new": 0, "baselined": 0})
            out[f.rule]["baselined"] += 1
        return out


def discover_files(repo: str, paths: Sequence[str],
                   missing_ok: bool = False) -> List[str]:
    """Expand the CLI path arguments into .py files (sorted,
    deduplicated). Directories recurse; __pycache__ is skipped. A
    path that exists as neither file nor directory is an ERROR — a
    typo'd CI invocation must not lint nothing and exit 0 — EXCEPT
    under ``missing_ok`` (the --changed-only mode): a changed-file
    list naturally contains files the change DELETED or renamed
    away, and those must be skipped, not fatal."""
    out = []
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(repo, p)
        if not os.path.exists(full):
            if missing_ok:
                continue
            raise ValueError(
                f"path {p!r} does not exist under {repo} — nothing "
                "would be linted")
        if os.path.isfile(full):
            if not full.endswith(".py"):
                raise ValueError(
                    f"path {p!r} is not a .py file — it would not "
                    "be linted")
            out.append(os.path.abspath(full))
        elif os.path.isdir(full):
            for root, dirs, files in os.walk(full):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                for fname in sorted(files):
                    if fname.endswith(".py"):
                        out.append(os.path.abspath(
                            os.path.join(root, fname)))
    return sorted(set(out))


def changed_files(repo: str) -> Optional[set]:
    """Repo-relative paths touched vs HEAD (staged, unstaged and
    untracked). None when git is unavailable — callers fall back to
    the full tree rather than silently linting nothing."""
    try:
        diff = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            cwd=repo, capture_output=True, text=True, timeout=30)
        untracked = subprocess.run(
            ["git", "ls-files", "-o", "--exclude-standard"],
            cwd=repo, capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if diff.returncode != 0:
        return None
    def parse(stdout: str) -> set:
        # one path per LINE (paths may contain spaces); git quotes
        # and escapes non-ASCII/space-odd names under core.quotepath
        # — decode those back to the literal path
        names = set()
        for line in stdout.splitlines():
            if not line:
                continue
            if line.startswith('"') and line.endswith('"'):
                line = line[1:-1].encode("latin-1", "replace") \
                    .decode("unicode_escape") \
                    .encode("latin-1", "replace").decode("utf-8",
                                                         "replace")
            names.add(line)
        return names

    names = parse(diff.stdout)
    if untracked.returncode == 0:
        names |= parse(untracked.stdout)
    return {n.replace(os.sep, "/") for n in names}


_suppression_cache: Dict[str, Suppressions] = {}


def _suppressions_for(repo: str, relpath: str) -> Suppressions:
    full = os.path.join(repo, relpath)
    try:
        mtime = os.path.getmtime(full)
    except OSError:
        return Suppressions("")
    cache_key = f"{full}:{mtime}"
    if cache_key not in _suppression_cache:
        try:
            with open(full, encoding="utf-8", errors="replace") as f:
                text = f.read()
        except OSError:
            text = ""
        _suppression_cache[cache_key] = Suppressions(text)
    return _suppression_cache[cache_key]


def _run_file_rules(m: ParsedModule, rule_ids: Sequence[str]
                    ) -> Tuple[List[Finding], Dict[str, float]]:
    """One module through the file-scope rules (+ its parse error):
    the ONE per-file implementation the serial and --jobs paths
    share, so a change to the pass cannot diverge them."""
    import time as _time
    from tools.graftlint.rules import ALL_RULES
    findings: List[Finding] = []
    timings: Dict[str, float] = {}
    if m.parse_error is not None:
        findings.append(m.parse_error)
    elif m.tree is not None:
        for rid in rule_ids:
            t0 = _time.perf_counter()
            findings.extend(ALL_RULES[rid]().check(m))
            timings[rid] = (timings.get(rid, 0.0)
                            + _time.perf_counter() - t0)
    return findings, timings


def _analyze_file_job(args: Tuple[str, str, Tuple[str, ...]]):
    """Worker for --jobs: parse one file, run the file-scope rules.
    Top-level so it pickles into a process pool. Returns
    ``(relpath, findings, per-rule timings)``."""
    repo, path, rule_ids = args
    m = ParsedModule(path, repo)
    findings, timings = _run_file_rules(m, rule_ids)
    return m.relpath, findings, timings


def run_lint(repo: str,
             paths: Sequence[str] = (PACKAGE_DIR,),
             rules: Optional[Sequence[str]] = None,
             baseline: Optional[Baseline] = None,
             changed_only: bool = False,
             jobs: int = 1,
             cache_path: Optional[str] = None) -> LintReport:
    import time as _time

    from tools.graftlint.rules import ALL_RULES

    repo = os.path.abspath(repo)
    selected = {r.upper() for r in rules} if rules else set(ALL_RULES)
    unknown = selected - set(ALL_RULES)
    if unknown:
        raise ValueError(
            f"unknown rule(s) {sorted(unknown)}; "
            f"available: {sorted(ALL_RULES)}")

    # under --changed-only a path argument may name a file the change
    # DELETED or renamed away — skip it instead of erroring
    all_files = discover_files(repo, paths, missing_ok=changed_only)
    changed = changed_files(repo) if changed_only else None
    files = all_files
    if changed is not None:
        files = [f for f in all_files
                 if os.path.relpath(f, repo).replace(os.sep, "/")
                 in changed]

    file_rules = tuple(rid for rid in sorted(selected)
                       if ALL_RULES[rid].scope == "file")
    repo_rules = [rid for rid in sorted(selected)
                  if ALL_RULES[rid].scope == "repo"]
    timings: Dict[str, float] = {}
    raw: List[Finding] = []
    parsed_by_path: Dict[str, ParsedModule] = {}

    cache = None
    if cache_path:
        from tools.graftlint.cache import LintCache, file_key
        cache = LintCache(cache_path)

    # ---- file-scope pass (cacheable, parallelizable) ----
    pending: List[str] = []
    keys: Dict[str, str] = {}
    for f in files:
        hit = None
        if cache is not None:
            rel = os.path.relpath(f, repo).replace(os.sep, "/")
            try:
                with open(f, encoding="utf-8",
                          errors="replace") as fh:
                    keys[f] = file_key(rel, fh.read())
            except OSError:
                keys[f] = ""
            if keys[f]:
                hit = cache.lookup(keys[f], file_rules)
        if hit is not None:
            raw.extend(hit)
        else:
            pending.append(f)
    if jobs > 1 and len(pending) > 1:
        import concurrent.futures
        with concurrent.futures.ProcessPoolExecutor(
                max_workers=jobs) as pool:
            for rel, findings, t in pool.map(
                    _analyze_file_job,
                    [(repo, f, file_rules) for f in pending],
                    chunksize=8):
                raw.extend(findings)
                for rid, dt in t.items():
                    timings[rid] = timings.get(rid, 0.0) + dt
                if cache is not None and keys.get(
                        os.path.join(repo, rel)):
                    cache.store(keys[os.path.join(repo, rel)],
                                file_rules, findings)
    else:
        for f in pending:
            m = ParsedModule(f, repo)
            parsed_by_path[f] = m
            findings, t = _run_file_rules(m, file_rules)
            for rid, dt in t.items():
                timings[rid] = timings.get(rid, 0.0) + dt
            raw.extend(findings)
            if cache is not None and keys.get(f):
                cache.store(keys[f], file_rules, findings)
    if cache is not None:
        cache.save()

    # ---- repo-scope pass (always live: cross-file by nature) ----
    def module_for(f: str) -> ParsedModule:
        m = parsed_by_path.get(f)
        if m is None:
            m = parsed_by_path[f] = ParsedModule(f, repo)
        return m

    full_ctx = None
    if repo_rules:
        modules = [module_for(f) for f in files]
        # (parse errors are owned by the file pass above — it runs,
        # or is served from cache, for every file in scope)
        ctx = RepoContext(repo,
                          [m for m in modules if m.tree is not None])
        full_ctx = ctx if changed is None else None
        for rid in repo_rules:
            rule = ALL_RULES[rid]()
            # repo-scope rules still honour --changed-only: with a
            # change set and nothing relevant touched, skip the pass
            if changed is not None and not any(
                    rule.repo_triggered(p) for p in changed):
                continue
            # a triggered repo-scope rule analyzes the FULL tree —
            # cross-file context (GL004's acquisition graph, the
            # GL008/GL010 call graph) must see unchanged modules or
            # an inversion/path through one is invisible — but only
            # findings in changed files are reported (the unchanged
            # half of a new inversion is a pre-existing site)
            if full_ctx is None:
                fm = [module_for(f) for f in all_files]
                full_ctx = RepoContext(
                    repo, [m for m in fm if m.tree is not None])
            t0 = _time.perf_counter()
            found = list(rule.check_repo(full_ctx))
            timings[rid] = (timings.get(rid, 0.0)
                            + _time.perf_counter() - t0)
            if changed is not None:
                found = [f for f in found if f.path in changed]
            raw.extend(found)

    kept, suppressed = [], 0
    for f in raw:
        if _suppressions_for(repo, f.path).suppressed(f.rule, f.line):
            suppressed += 1
        else:
            kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule, f.message))

    base = baseline or Baseline()
    new, old = base.split(kept)
    return LintReport(new=new, baselined=old, suppressed=suppressed,
                      rules_run=sorted(selected),
                      files_checked=len(files),
                      timings=timings,
                      cache_hits=cache.hits if cache else 0,
                      cache_misses=cache.misses if cache else 0)


# ---------------------------------------------------------------------------
# output
# ---------------------------------------------------------------------------

def format_text(report: LintReport) -> str:
    lines = [f.render() for f in report.new]
    lines.append(
        f"graftlint: {len(report.new)} new finding(s), "
        f"{len(report.baselined)} baselined, "
        f"{report.suppressed} suppressed "
        f"({report.files_checked} file(s), "
        f"rules {','.join(report.rules_run)})")
    return "\n".join(lines)


def format_json(report: LintReport) -> str:
    def enc(f: Finding) -> dict:
        return {"rule": f.rule, "path": f.path, "line": f.line,
                "symbol": f.symbol, "message": f.message,
                "key": f.key}
    return json.dumps(
        {"ok": report.ok,
         "new": [enc(f) for f in report.new],
         "baselined": [enc(f) for f in report.baselined],
         "suppressed": report.suppressed,
         "files_checked": report.files_checked,
         "rules_run": report.rules_run},
        indent=1)


def format_stats(report: LintReport,
                 baseline: Optional[Baseline] = None) -> str:
    """The ratchet report: per-rule current findings vs the baseline
    allowance, so a PR can cite "N fixed, M baselined"."""
    from tools.graftlint.rules import ALL_RULES
    allowance: Dict[str, int] = {}
    for key, e in (baseline.entries if baseline else {}).items():
        allowance[key.split("|", 1)[0]] = (
            allowance.get(key.split("|", 1)[0], 0) + e["count"])
    per = report.per_rule()
    rows = [("rule", "current", "baselined", "new", "allowance",
             "wall_s")]
    for rid in sorted(set(per) | set(allowance)):
        c = per.get(rid, {"new": 0, "baselined": 0})
        title = getattr(ALL_RULES.get(rid), "title", "")
        rows.append((f"{rid} {title}".strip(),
                     str(c["new"] + c["baselined"]),
                     str(c["baselined"]), str(c["new"]),
                     str(allowance.get(rid, 0)),
                     f"{report.timings.get(rid, 0.0):.3f}"))
    widths = [max(len(r[i]) for r in rows) for i in range(6)]
    out = ["  ".join(cell.ljust(widths[i])
                     for i, cell in enumerate(row)).rstrip()
           for row in rows]
    fixed = sum(max(0, allowance.get(rid, 0)
                    - per.get(rid, {}).get("baselined", 0))
                for rid in allowance)
    out.append(f"total: {len(report.new) + len(report.baselined)} "
               f"finding(s) ({len(report.new)} new, "
               f"{len(report.baselined)} baselined, "
               f"{fixed} baseline slot(s) no longer hit); rule "
               f"wall time {sum(report.timings.values()):.3f}s")
    if report.cache_hits or report.cache_misses:
        out.append(f"cache: {report.cache_hits} hit(s), "
                   f"{report.cache_misses} miss(es)")
    return "\n".join(out)
