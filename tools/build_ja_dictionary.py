"""Build the bundled Japanese core dictionary for the lattice
segmenter.

Hand-curated: Japanese segmentation is driven by the CLOSED classes
(particles, auxiliaries, copulas — a few dozen morphemes cover most
token boundaries), so a compact curated core plus the segmenter's
character-class unknown-word grouping handles real text. Counts are
rough relative frequencies; tags feed the connection-cost matrix.

Reproducible: `python tools/build_ja_dictionary.py` regenerates
deeplearning4j_tpu/nlp/data/ja_core.tsv.gz byte-for-byte.
"""

import gzip
import io
import os

entries = []


def add(tag, count, *words):
    for w in words:
        entries.append((w, count, tag))


# --- particles (closed class, dominate JP segmentation) ---
add("prt", 900000, "は", "が", "を", "に", "で", "と", "の", "も")
add("prt", 400000, "へ", "から", "まで", "より", "や", "か", "ね",
    "よ", "な", "ば", "ので", "のに", "けど", "けれど", "って",
    "だけ", "しか", "ほど", "くらい", "ぐらい", "など", "なら",
    "ずつ", "こそ", "さえ", "でも", "には", "では", "とは", "への")
# --- copulas / auxiliaries / polite endings ---
add("aux", 700000, "です", "だ", "である", "ます", "ました", "でした",
    "ません", "だった", "じゃない", "ではない", "でしょう", "だろう")
add("aux", 300000, "ない", "たい", "れる", "られる", "せる", "させる",
    "そうだ", "ようだ", "らしい", "みたい", "はず", "べき", "つもり")
# --- common verbs (dictionary + common conjugated forms) ---
add("v", 500000, "する", "した", "して", "します", "いる", "います",
    "いた", "いて", "ある", "あります", "あった", "なる", "なります",
    "なった", "なって", "できる", "できます", "できた")
add("v", 200000, "行く", "行きます", "行った", "来る", "来ます", "来た",
    "見る", "見ます", "見た", "見て", "聞く", "聞いた", "話す",
    "話した", "読む", "読んだ", "書く", "書いた", "食べる", "食べた",
    "飲む", "飲んだ", "買う", "買った", "売る", "使う", "使った",
    "作る", "作った", "思う", "思います", "思った", "知る", "知って",
    "分かる", "分かります", "分かった", "言う", "言った", "言います",
    "持つ", "持って", "待つ", "待って", "歩く", "走る", "帰る",
    "帰った", "入る", "出る", "出た", "会う", "会った", "働く",
    "働いて", "働いた", "学ぶ", "学んで", "教える", "教えて",
    "始まる", "始める", "終わる", "住む", "住んで",
    "飲みます", "食べます", "読みます", "書きます", "聞きます",
    "話します", "買います", "使います", "作ります", "帰ります",
    "死ぬ", "生きる", "遊ぶ", "泳ぐ", "取る", "置く", "呼ぶ",
    "送る", "届く", "開く", "閉じる", "立つ", "座る", "寝る",
    "起きる", "着る", "脱ぐ", "洗う", "切る", "貸す", "借りる",
    "返す", "忘れる", "覚える", "考える", "考えた", "感じる",
    "信じる", "調べる", "続く", "続ける", "変わる", "変える",
    "動く", "止まる", "止める", "示す", "述べる", "用いる",
    "含む", "求める", "得る", "与える", "受ける", "受けた",
    "行う", "行った", "行われる", "見られる", "される", "されて",
    "された", "されている", "している", "していた", "していて")
# --- pronouns / demonstratives ---
add("pron", 400000, "私", "僕", "俺", "君", "彼", "彼女", "あなた",
    "誰", "何", "これ", "それ", "あれ", "どれ", "ここ", "そこ",
    "あそこ", "どこ", "この", "その", "あの", "どの", "こちら",
    "そちら", "みんな", "皆", "自分", "我々", "彼ら")
# --- common nouns ---
add("n", 250000, "人", "日", "時", "年", "月", "週", "分", "秒",
    "今日", "明日", "昨日", "今", "朝", "昼", "夜", "午前", "午後",
    "毎日", "毎週", "毎月", "毎年", "毎朝", "毎晩",
    "時間", "時代", "場所", "家", "部屋", "水", "火", "木", "金",
    "土", "空", "海", "山", "川", "道", "駅", "町", "市", "村",
    "国", "世界", "日本", "日本語", "英語", "中国語", "語",
    "東京", "東京都", "京都", "大阪", "中国", "米国",
    "言葉", "言語", "話", "声", "音", "色", "形", "名前", "意味",
    "問題", "質問", "答え", "理由", "結果", "原因", "方法", "目的",
    "仕事", "会社", "学校", "大学", "先生", "学生", "生徒", "友達",
    "家族", "父", "母", "子供", "男", "女", "犬", "猫", "鳥", "魚",
    "本", "紙", "字", "文", "文章", "写真", "絵", "歌", "車",
    "電車", "飛行機", "船", "自転車", "電話", "手紙", "お金", "店",
    "料理", "食べ物", "飲み物", "茶", "米", "肉", "野菜", "果物",
    "すもも", "もも", "桃", "天気", "雨", "雪", "風", "雲",
    "春", "夏", "秋", "冬", "勉強", "研究", "生命", "起源",
    "心", "体", "頭", "顔", "目", "耳", "口", "手", "足",
    "力", "気", "気持ち", "科学", "技術",
    "自然", "社会", "政治", "経済", "歴史", "文化", "芸術", "音楽",
    "情報", "数", "数字", "計算", "機械", "電気", "物", "事",
    "こと", "もの", "ところ", "とき", "ため", "よう", "うち",
    "中", "外", "上", "下", "前", "後", "左", "右", "間", "隣",
    "都", "県", "府", "区")
# --- adjectives ---
add("adj", 150000, "大きい", "小さい", "新しい", "古い", "高い",
    "安い", "低い", "長い", "短い", "広い", "狭い", "早い", "速い",
    "遅い", "多い", "少ない", "良い", "いい", "悪い", "暑い", "寒い",
    "暖かい", "涼しい", "熱い", "冷たい", "強い", "弱い", "重い",
    "軽い", "近い", "遠い", "白い", "黒い", "赤い", "青い", "明るい",
    "暗い", "難しい", "易しい", "簡単", "便利", "不便", "有名",
    "静か", "元気", "大切", "大事", "必要", "可能", "特別",
    "美しい", "楽しい", "嬉しい", "悲しい", "面白い", "つまらない")
# --- adverbs / conjunctions ---
add("adv", 200000, "とても", "すごく", "少し", "ちょっと", "たくさん",
    "もっと", "一番", "全部", "全て", "すべて", "いつも", "時々",
    "たまに", "まだ", "もう", "すぐ", "ゆっくり", "きっと", "多分",
    "たぶん", "必ず", "本当に", "実は", "例えば", "特に", "約",
    "そして", "しかし", "でも", "だから", "それで", "また", "または",
    "つまり", "ただ", "もし", "なぜ", "どう", "こう", "そう", "ああ")
# --- numbers / counters ---
add("num", 300000, "一", "二", "三", "四", "五", "六", "七", "八",
    "九", "十", "百", "千", "万", "億", "〇", "零")
add("n", 150000, "一つ", "二つ", "三つ", "円", "歳", "人々", "回",
    "度", "番", "号", "個", "匹", "冊", "枚")
# --- katakana loanwords ---
add("n", 120000, "コンピュータ", "コンピューター", "インターネット",
    "システム", "データ", "ソフト", "ソフトウェア", "ハードウェア",
    "プログラム", "ネットワーク", "サービス", "ニュース", "テレビ",
    "ラジオ", "カメラ", "ビデオ", "ゲーム", "スポーツ", "サッカー",
    "テニス", "ホテル", "レストラン", "メニュー", "コーヒー",
    "ビール", "ワイン", "パン", "バス", "タクシー", "ドア", "ビル",
    "エネルギー", "モデル", "クラス", "テスト", "ページ", "チーム",
    "グループ", "センター", "メール", "ファイル", "ユーザー",
    "デザイン", "プロジェクト", "アイデア", "イメージ", "レベル")

# connection costs: discourage particle-particle chains, reward
# noun→particle / particle→verb etc. (the Kuromoji matrix idea at
# tag granularity)
CONNS = [("prt", "prt", 2.0), ("n", "prt", -0.5),
         ("pron", "prt", -0.5), ("prt", "v", -0.3),
         ("prt", "n", -0.3), ("aux", "aux", 0.5),
         ("v", "aux", -0.5), ("num", "n", -0.3)]

HEADER = """\
# Japanese core dictionary for the lattice segmenter.
# Hand-curated closed-class morphemes (particles, auxiliaries) +
# common content words; counts are rough relative frequencies.
# Format: word<TAB>count<TAB>tag; @conn<TAB>left<TAB>right<TAB>cost.
# Regenerate with: python tools/build_ja_dictionary.py
"""


def main():
    buf = io.StringIO()
    buf.write(HEADER)
    seen = set()
    for w, c, t in entries:
        if w in seen:
            continue
        seen.add(w)
        buf.write(f"{w}\t{c}\t{t}\n")
    for l, r, c in CONNS:
        buf.write(f"@conn\t{l}\t{r}\t{c}\n")
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "deeplearning4j_tpu", "nlp",
        "data", "ja_core.tsv.gz")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "wb") as raw:
        with gzip.GzipFile(fileobj=raw, mode="wb", compresslevel=9,
                           mtime=0) as f:
            f.write(buf.getvalue().encode("utf-8"))
    print(f"{out}: {len(seen)} entries")


if __name__ == "__main__":
    main()
