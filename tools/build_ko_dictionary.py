"""Build the bundled Korean core dictionary for the lattice
segmenter.

Hand-curated, same philosophy as the Japanese core
(tools/build_ja_dictionary.py): Korean eojeol = stem + CLOSED-class
attachments (josa particles, verb/adjective endings), so a compact
curated core of those attachments plus the segmenter's hangul-run
unknown grouping splits real text — an out-of-dictionary stem like
대학교 groups as one unknown run that ends exactly where a known
josa (에서) begins, yielding 대학교|에서 (the reference's Korean pack
wraps an external analyzer for the same job,
deeplearning4j-nlp-korean/.../KoreanTokenizer.java). Conjugation is
covered the curated way: common conjugated surface forms (했습니다,
먹었다, ...) are entries, because Korean fuses stem+ending inside
syllable blocks (하+였→했) and a syllable-level lattice cannot split
below the block.

Reproducible: `python tools/build_ko_dictionary.py` regenerates
deeplearning4j_tpu/nlp/data/ko_core.tsv.gz byte-for-byte.
"""

import gzip
import io
import os

entries = []


def add(tag, count, *words):
    for w in words:
        entries.append((w, count, tag))


# --- josa: noun particles (closed class, dominate KO segmentation) ---
add("josa", 900000, "은", "는", "이", "가", "을", "를", "에", "의",
    "도", "로", "와", "과")
add("josa", 400000, "에서", "에게", "에게서", "한테", "한테서", "께",
    "께서", "으로", "로서", "으로서", "로써", "으로써", "하고", "이랑",
    "랑", "만", "까지", "부터", "보다", "처럼", "같이", "마다", "조차",
    "마저", "밖에", "이나", "나", "든지", "이든지", "라도", "이라도",
    "야말로", "이야말로", "은커녕", "는커녕", "에는", "에서는", "에도",
    "에서도", "과의", "와의", "로의", "으로의", "마는", "요")
# --- verb/adjective endings + auxiliaries (common surface forms) ---
add("end", 700000, "다", "요", "까", "죠", "네요", "어요", "아요",
    "여요", "습니다", "습니까", "ㅂ니다", "입니다", "입니까", "합니다",
    "합니까", "였다", "이었다", "이다", "인", "인데", "이지만")
add("end", 300000, "았다", "었다", "겠다", "았습니다", "었습니다",
    "겠습니다", "고", "서", "면", "으면", "지만", "는데", "니까",
    "으니까", "려고", "으려고", "도록", "게", "지", "기", "음", "ㅁ",
    "는", "은", "을", "던", "았던", "었던", "어서", "아서", "여서",
    "고서", "면서", "으면서", "자", "자마자", "거나", "든가", "느냐",
    "으냐", "는지", "은지", "을지", "네", "군요", "구나", "답니다",
    "랍니다", "시", "으시", "세요", "으세요", "십시오", "으십시오")
# --- common verbs (dictionary + common conjugated forms) ---
add("v", 500000, "하다", "한다", "했다", "합니다", "했습니다", "하고",
    "하는", "하면", "해서", "해요", "해", "하지", "하기", "하여",
    "있다", "있는", "있습니다", "있어요", "있고", "있지", "없다",
    "없는", "없습니다", "없어요", "되다", "된다", "됩니다", "되었다",
    "됐다", "되는", "되어", "돼")
add("v", 200000, "가다", "간다", "갔다", "갑니다", "가는", "가고",
    "오다", "온다", "왔다", "옵니다", "오는", "오고", "보다", "본다",
    "봤다", "봅니다", "보는", "보고", "먹다", "먹는", "먹었다",
    "먹습니다", "먹고", "마시다", "마셨다", "듣다", "들었다", "듣고",
    "말하다", "말했다", "말합니다", "읽다", "읽었다", "읽고", "쓰다",
    "썼다", "쓰고", "사다", "샀다", "팔다", "쓰이다", "만들다",
    "만든다", "만들었다", "만들고", "생각하다", "생각한다",
    "생각했다", "알다", "안다", "알았다", "알고", "압니다", "모르다",
    "모른다", "몰랐다", "배우다", "배웠다", "가르치다", "가르쳤다",
    "살다", "산다", "살았다", "삽니다", "살고", "죽다", "일하다",
    "일했다", "공부하다", "공부했다", "연구하다", "연구했다",
    "사용하다", "사용한다", "사용했다", "이용하다", "받다", "받았다",
    "받는", "주다", "준다", "주었다", "줬다", "주고", "얻다",
    "찾다", "찾았다", "찾고", "잃다", "만나다", "만났다", "떠나다",
    "들어가다", "들어오다", "나가다", "나오다", "나왔다", "앉다",
    "서다", "섰다", "눕다", "자다", "잤다", "일어나다", "일어났다",
    "입다", "입었다", "벗다", "씻다", "기다리다", "기다렸다",
    "걷다", "걸었다", "뛰다", "달리다", "타다", "탔다", "내리다",
    "열다", "열었다", "닫다", "닫았다", "시작하다", "시작했다",
    "시작된다", "끝나다", "끝났다", "계속하다", "바꾸다", "바뀌다",
    "변하다", "움직이다", "멈추다", "보내다", "보냈다", "도착하다",
    "느끼다", "느꼈다", "믿다", "잊다", "잊었다", "기억하다",
    "원하다", "원한다", "바라다", "좋아하다", "좋아한다",
    "좋아했다", "싫어하다", "사랑하다", "사랑한다", "사랑했다",
    "나타나다", "보이다", "보인다", "들리다", "생기다", "생겼다",
    "가지다", "가진", "갖다", "놓다", "두다", "넣다", "꺼내다",
    "돌아가다", "돌아오다", "올라가다", "내려가다", "지나다",
    "지났다", "남다", "남았다", "따르다", "따른", "따라", "위하다",
    "위한", "위해", "대하다", "대한", "대해", "통하다", "통한",
    "통해", "의하다", "의한", "의해", "관하다", "관한", "관해")
# --- pronouns / demonstratives ---
add("pron", 400000, "나", "저", "너", "우리", "저희", "그", "그녀",
    "그들", "누구", "누가", "무엇", "뭐", "이것", "그것", "저것",
    "어느것", "여기", "거기", "저기", "어디", "언제", "자기", "자신",
    "서로", "모두", "여러분", "당신")
# --- determiners ---
add("det", 300000, "이", "그", "저", "어느", "어떤", "무슨", "모든",
    "여러", "몇", "새", "온", "각", "전", "현")
# --- common nouns ---
add("n", 250000, "사람", "사람들", "시간", "년", "월", "일", "주",
    "시", "분", "초", "오늘", "내일", "어제", "지금", "아침", "점심",
    "저녁", "밤", "오전", "오후", "매일", "매주", "매년",
    "때", "때문", "경우", "시대", "시기", "동안", "순간",
    "집", "방", "학교", "대학", "대학교", "학생", "선생님", "교수",
    "친구", "가족", "아버지", "어머니", "부모", "아들", "딸", "아이",
    "아이들", "남자", "여자", "남성", "여성", "소년", "소녀",
    "나라", "한국", "한국어", "한글", "서울", "미국", "중국", "일본",
    "북한", "세계", "세상", "국가", "국민", "정부", "도시", "지역",
    "마을", "거리", "길", "역", "공항", "병원", "은행", "시장",
    "가게", "식당", "회사", "공장", "사무실", "교실", "도서관",
    "말", "언어", "단어", "글", "글자", "문장", "이름", "뜻", "의미",
    "이야기", "소리", "목소리", "질문", "대답", "문제", "답",
    "이유", "원인", "결과", "방법", "방식", "목적", "계획", "생각",
    "마음", "기분", "느낌", "사랑", "행복", "희망", "꿈", "믿음",
    "일", "직업", "돈", "값", "가격", "물건", "선물",
    "물", "불", "흙", "공기", "바람", "비", "눈", "구름", "하늘",
    "땅", "산", "강", "바다", "섬", "숲", "나무", "꽃", "풀",
    "동물", "개", "고양이", "새", "물고기", "소", "말",
    "밥", "음식", "고기", "야채", "채소", "과일", "빵", "국",
    "김치", "커피", "차", "술", "우유", "물건",
    "책", "신문", "잡지", "편지", "종이", "사진", "그림", "영화",
    "음악", "노래", "춤", "게임", "운동", "축구", "야구",
    "차", "자동차", "기차", "버스", "지하철", "배", "비행기",
    "자전거", "전화", "휴대폰", "컴퓨터", "인터넷", "텔레비전",
    "뉴스", "프로그램", "정보", "자료", "기술", "과학", "수학",
    "역사", "문화", "예술", "교육", "경제", "정치", "사회", "법",
    "종교", "철학", "연구", "공부", "수업", "시험", "숙제",
    "생명", "기원", "자연", "환경", "우주", "지구", "태양", "달",
    "별", "빛", "색", "모양", "크기", "무게", "힘", "에너지",
    "몸", "머리", "얼굴", "눈", "코", "입", "귀", "목", "손",
    "발", "팔", "다리", "가슴", "마음", "피", "뼈",
    "날씨", "봄", "여름", "가을", "겨울", "날", "주말", "휴일",
    "처음", "마지막", "다음", "이번", "지난", "앞", "뒤", "위",
    "아래", "안", "밖", "옆", "사이", "가운데", "중", "속", "근처",
    "왼쪽", "오른쪽", "동쪽", "서쪽", "남쪽", "북쪽",
    "것", "수", "데", "바", "줄", "적", "뿐", "만큼", "정도",
    "이상", "이하", "전체", "부분", "중심", "내용", "형태", "상태",
    "상황", "조건", "기회", "경험", "능력", "실력", "노력", "성공",
    "실패", "변화", "발전", "관계", "관심", "영향", "차이")
# --- adjectives / descriptive verbs (common surface forms) ---
add("adj", 150000, "크다", "큰", "작다", "작은", "많다", "많은",
    "많이", "적다", "적은", "좋다", "좋은", "좋습니다", "좋아요",
    "나쁘다", "나쁜", "새롭다", "새로운", "오래되다", "오래된",
    "높다", "높은", "낮다", "낮은", "길다", "긴", "짧다", "짧은",
    "넓다", "넓은", "좁다", "빠르다", "빠른", "빨리", "느리다",
    "느린", "천천히", "어렵다", "어려운", "쉽다", "쉬운", "무겁다",
    "무거운", "가볍다", "가벼운", "멀다", "먼", "가깝다", "가까운",
    "뜨겁다", "뜨거운", "차갑다", "차가운", "덥다", "더운", "춥다",
    "추운", "따뜻하다", "따뜻한", "시원하다", "밝다", "밝은",
    "어둡다", "어두운", "희다", "흰", "검다", "검은", "붉다",
    "붉은", "푸르다", "푸른", "예쁘다", "예쁜", "아름답다",
    "아름다운", "멋있다", "즐겁다", "즐거운", "기쁘다", "기쁜",
    "슬프다", "슬픈", "재미있다", "재미있는", "재미없다",
    "중요하다", "중요한", "필요하다", "필요한", "가능하다",
    "가능한", "특별하다", "특별한", "유명하다", "유명한",
    "간단하다", "간단한", "복잡하다", "복잡한", "강하다", "강한",
    "약하다", "약한", "젊다", "젊은", "늙다", "늙은", "어리다",
    "어린", "같다", "같은", "다르다", "다른", "비슷하다", "비슷한")
# --- adverbs / conjunctions ---
add("adv", 200000, "매우", "아주", "너무", "정말", "진짜", "조금",
    "좀", "더", "덜", "가장", "제일", "거의", "약", "다", "또",
    "다시", "함께", "같이", "혼자", "항상", "늘", "자주", "가끔",
    "때때로", "아직", "이미", "벌써", "곧", "바로", "먼저", "나중에",
    "요즘", "최근", "아마", "혹시", "꼭", "반드시", "물론", "사실",
    "특히", "예를", "결국", "드디어", "갑자기", "천천히", "잘",
    "못", "안", "그리고", "그러나", "하지만", "그런데", "그래서",
    "그러면", "그럼", "그러므로", "따라서", "또한", "또는", "혹은",
    "즉", "만약", "만일", "왜", "어떻게", "이렇게", "그렇게",
    "저렇게", "왜냐하면", "예", "아니", "아니요", "네")
# --- numbers / counters ---
add("num", 300000, "일", "이", "삼", "사", "오", "육", "칠", "팔",
    "구", "십", "백", "천", "만", "억", "영", "공", "하나", "둘",
    "셋", "넷", "다섯", "여섯", "일곱", "여덟", "아홉", "열",
    "스물", "서른", "마흔", "쉰", "한", "두", "세", "네")
add("cnt", 150000, "개", "명", "분", "마리", "번", "살", "원",
    "권", "장", "대", "잔", "병", "그릇", "켤레", "벌", "채",
    "송이", "시간", "년", "월", "일", "주", "달", "번째", "가지")

# connection costs (tag granularity): noun/pronoun → josa is THE
# Korean boundary; stem-ish → ending likewise; two josa in a row is
# unusual (에+는 style compounds are their own entries)
CONNS = [("n", "josa", -0.5), ("pron", "josa", -0.5),
         ("num", "cnt", -0.5), ("cnt", "josa", -0.4),
         ("det", "n", -0.4), ("v", "end", -0.5),
         ("adj", "end", -0.5), ("josa", "josa", 2.0),
         ("end", "end", 1.0), ("josa", "v", -0.3),
         ("josa", "n", -0.2), ("end", "n", -0.2),
         # unknown STEM + known attachment is the expected eojeol
         # shape: the bonus must outweigh the unknown length scaling
         # (0.3 * unknown_cost per extra char) plus the attachment's
         # own word cost, or 블록체인+을 over-groups into one token
         ("unk", "josa", -2.5), ("unk", "end", -2.0)]

HEADER = """\
# Korean core dictionary for the lattice segmenter.
# Hand-curated closed-class morphemes (josa particles, endings) +
# common content words; counts are rough relative frequencies.
# Conjugated surface forms are entries (syllable blocks fuse
# stem+ending, e.g. 하+였→했, so the lattice cannot split below the
# block). Format: word<TAB>count<TAB>tag;
# @conn<TAB>left<TAB>right<TAB>cost.
# Regenerate with: python tools/build_ko_dictionary.py
"""


def main():
    buf = io.StringIO()
    buf.write(HEADER)
    # The lattice dictionary holds ONE (cost, tag) per surface form,
    # so ambiguous morphemes (은/는/을 are both josa and verb endings;
    # 시간/년/월/일 both nouns and counters) keep their FIRST listing —
    # the add() calls above are ordered most-common-role-first on
    # purpose. Dropped duplicates are printed so a curation change
    # that silently loses a tag is visible.
    seen = {}
    dropped = []
    for w, c, t in entries:
        if w in seen:
            if seen[w] != t:
                dropped.append(f"{w} ({t}; kept {seen[w]})")
            continue
        seen[w] = t
        buf.write(f"{w}\t{c}\t{t}\n")
    for l, r, c in CONNS:
        buf.write(f"@conn\t{l}\t{r}\t{c}\n")
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "deeplearning4j_tpu", "nlp",
        "data", "ko_core.tsv.gz")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "wb") as raw:
        with gzip.GzipFile(fileobj=raw, mode="wb", compresslevel=9,
                           mtime=0) as f:
            f.write(buf.getvalue().encode("utf-8"))
    print(f"{out}: {len(seen)} entries")
    if dropped:
        print(f"dropped {len(dropped)} ambiguous-role duplicates "
              f"(first-listed role wins): {', '.join(dropped)}")


if __name__ == "__main__":
    main()
