#!/usr/bin/env python
"""Lint: every "N.Nx" perf claim in the docs must be measured, and
every metric name the docs cite must exist in the code.

Two rounds in a row shipped prose speedups ("4.1x over exact masked
attention") whose numbers no bench artifact ever recorded — the
round-5 verdict's central complaint. This lint makes that impossible
going forward: every ``N.Nx`` / ``N.N×`` multiplier claimed in
README.md or COMPONENTS.md must correspond to a number present in
(or derivable from) the committed ``BENCH_DETAIL.json``:

- the value of an explicit RATIO key in the artifact (any key whose
  name contains ``vs_`` — ``vs_baseline``, ``vs_production_kernel``,
  ``vs_exact_masked``, ``fused_vs_bounded``, ...), matched at the
  claim's own precision (a "3.3x" claim matches a measured 3.316; a
  "3.3x" claim against a measured 2.1 fails);
- ratios between two configs' ``value`` fields sharing BOTH a unit
  and a metric family (the metric's first word — the "bf16 ResNet50
  is 1.44x the f32 ResNet50" class of claim).

Matching is deliberately NOT "any number anywhere in the artifact":
with hundreds of raw values and cross-config ratios, most fabricated
multipliers would collide with something by accident and the lint
would guarantee nothing.

Lines containing the word "target" are exempt — a declared goal
("BASELINE target: >= 0.70x of flax") is not a measurement claim.

**Stale metric names** are the same bug class for observability docs:
a README that tells operators to alert on ``serving_latency_seconds``
after the code renamed it is worse than no README. Every backticked
identifier in README/COMPONENTS that LOOKS like a registry metric
(snake_case ending in a Prometheus unit/kind suffix — ``_total``,
``_seconds``, ``_bytes``, ``_depth``, ``_firing``) must match a
metric-name string literal somewhere under ``deeplearning4j_tpu/``
(f-string name templates like ``f"{name}_queue_depth"`` match as
wildcards).

**Stale chaos-site names** joined with the chaos PR: inside any doc
section whose heading mentions fault injection / chaos, every
backticked dotted token (``checkpoint.write``, ``data.fetch``, ...)
must exist as a string literal under the package — the documented
fault-plan schema must keep matching the code's injection sites.

Run: ``python tools/check_perf_claims.py [--repo DIR]``; exit 0 =
clean. Wired into the tier-1 test tier via tests/test_observability.py
(perf claims) and tests/test_health.py (metric names).
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import re
import sys
from typing import List, Tuple

DOC_FILES = ["README.md", "COMPONENTS.md"]
ARTIFACT = "BENCH_DETAIL.json"

# an N.Nx multiplier claim: requires a decimal point (plain "2x256"
# tensor shapes and "8x" core counts are not perf claims in this
# repo's docs; the measured-claim convention is one decimal or more)
CLAIM_RE = re.compile(r"(\d+\.\d+)\s*[x×]")


def _collect_ratio_keys(obj, out: List[float]) -> None:
    if isinstance(obj, dict):
        for k, v in obj.items():
            if "vs_" in str(k) and isinstance(v, (int, float)) \
                    and not isinstance(v, bool):
                out.append(float(v))
            else:
                _collect_ratio_keys(v, out)
    elif isinstance(obj, list):
        for v in obj:
            _collect_ratio_keys(v, out)


def measured_numbers(detail: dict) -> List[float]:
    """Legitimate multiplier sources only: explicit ``*vs_*`` ratio
    keys anywhere in the artifact, plus cross-config ``value`` ratios
    within one (unit, metric-family) pair — NOT every raw number."""
    out: List[float] = []
    _collect_ratio_keys(detail, out)
    configs = detail.get("configs", [])
    by_family = {}
    for c in configs:
        if isinstance(c.get("value"), (int, float)) and c.get("unit"):
            family = (c["unit"],
                      str(c.get("metric", "")).split(" ")[0])
            by_family.setdefault(family, []).append(float(c["value"]))
    for vals in by_family.values():
        for a, b in itertools.permutations(vals, 2):
            if b:
                out.append(a / b)
    return out


def claim_matches(claim: float, ndecimals: int,
                  numbers: List[float]) -> bool:
    tol = 10.0 ** (-ndecimals)
    return any(abs(n - claim) <= tol for n in numbers)


def find_claims(path: str) -> List[Tuple[int, str, float, int]]:
    """(line_no, line, claim_value, n_decimals) for each N.Nx."""
    claims = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            if "target" in line.lower():
                continue
            for m in CLAIM_RE.finditer(line):
                txt = m.group(1)
                claims.append((i, line.rstrip(), float(txt),
                               len(txt.split(".")[1])))
    return claims


# ---------------------------------------------------------------------------
# stale metric names
# ---------------------------------------------------------------------------

PACKAGE_DIR = "deeplearning4j_tpu"

# suffixes that mark a backticked doc token as a metric-name citation
METRIC_SUFFIXES = ("_total", "_seconds", "_bytes", "_depth",
                   "_firing", "_state")
_SUFFIX_ALT = "|".join(METRIC_SUFFIXES)

# `serving_requests_total`-style citations in docs
DOC_METRIC_RE = re.compile(
    r"`([a-z][a-z0-9_]*(?:%s))`" % _SUFFIX_ALT)

# metric-name string literals in source, including f-string templates
# (f"{name}_queue_depth" — the {…} part matches any label-ish token)
SRC_METRIC_RE = re.compile(
    r"""["']([A-Za-z0-9_{}]*(?:%s))["']""" % _SUFFIX_ALT)


def registered_metric_patterns(repo: str) -> List[re.Pattern]:
    """Compile every metric-name literal under the package into a
    matcher; ``{...}`` f-string holes become wildcards."""
    patterns = set()
    for root, _dirs, files in os.walk(os.path.join(repo, PACKAGE_DIR)):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            with open(os.path.join(root, fname),
                      encoding="utf-8", errors="replace") as f:
                src = f.read()
            for m in SRC_METRIC_RE.finditer(src):
                patterns.add(m.group(1))
    out = []
    for p in sorted(patterns):
        rx = re.escape(p).replace(r"\{", "{").replace(r"\}", "}")
        rx = re.sub(r"\{[^{}]*\}", r"[a-zA-Z0-9_/.-]+", rx)
        out.append(re.compile(rx + r"\Z"))
    return out


def find_doc_metric_names(path: str) -> List[Tuple[int, str]]:
    names = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            for m in DOC_METRIC_RE.finditer(line):
                names.append((i, m.group(1)))
    return names


def check_metric_names(repo: str) -> List[str]:
    patterns = registered_metric_patterns(repo)
    errors = []
    for doc in DOC_FILES:
        path = os.path.join(repo, doc)
        if not os.path.exists(path):
            continue
        for line_no, name in find_doc_metric_names(path):
            if not any(p.match(name) for p in patterns):
                errors.append(
                    f"{doc}:{line_no}: metric `{name}` is cited in "
                    f"the docs but registered nowhere under "
                    f"{PACKAGE_DIR}/ — stale name?")
    return errors


# ---------------------------------------------------------------------------
# stale chaos-site names
# ---------------------------------------------------------------------------

# the docs' fault-injection sections cite injection sites as
# backticked dotted tokens (`checkpoint.write`, `data.fetch`, ...);
# each must exist as a string literal under the package, or the
# documented plan schema silently stopped matching the code
DOC_SITE_RE = re.compile(r"`([a-z][a-z0-9_]*(?:\.[a-z0-9_]+)+)`")
SRC_SITE_RE = re.compile(
    r"""["']([a-z][a-z0-9_]*(?:\.[a-z0-9_]+)+)["']""")

# dotted doc tokens that are file references, not site names
_SITE_EXT_SKIP = {"py", "json", "jsonl", "md", "zip", "npz", "npy",
                  "txt", "ini", "csv", "bin", "gz", "log", "html",
                  "h5", "yaml", "yml"}


def find_doc_site_names(path: str) -> List[Tuple[int, str]]:
    """Backticked dotted tokens inside any section whose heading
    mentions fault injection / chaos (scoped: a dotted token
    elsewhere in the docs — `np.ndarray`, module paths — is not a
    site citation). Fenced code blocks are skipped entirely: a shell
    comment's leading '#' is not a markdown heading and must not
    toggle the section scope."""
    names = []
    in_section = False
    in_fence = False
    with open(path) as f:
        for i, line in enumerate(f, 1):
            if line.lstrip().startswith("```"):
                in_fence = not in_fence
                continue
            if in_fence:
                continue
            if re.match(r"#+\s", line):
                low = line.lower()
                in_section = ("fault injection" in low
                              or "chaos" in low)
                continue
            if not in_section:
                continue
            for m in DOC_SITE_RE.finditer(line):
                token = m.group(1)
                if token.rsplit(".", 1)[-1] in _SITE_EXT_SKIP:
                    continue
                names.append((i, token))
    return names


def registered_site_literals(repo: str) -> set:
    literals = set()
    for root, _dirs, files in os.walk(os.path.join(repo, PACKAGE_DIR)):
        for fname in files:
            if not fname.endswith(".py"):
                continue
            with open(os.path.join(root, fname),
                      encoding="utf-8", errors="replace") as f:
                src = f.read()
            for m in SRC_SITE_RE.finditer(src):
                literals.add(m.group(1))
    return literals


def check_site_names(repo: str) -> List[str]:
    literals = registered_site_literals(repo)
    errors = []
    for doc in DOC_FILES:
        path = os.path.join(repo, doc)
        if not os.path.exists(path):
            continue
        for line_no, name in find_doc_site_names(path):
            if name not in literals:
                errors.append(
                    f"{doc}:{line_no}: chaos site `{name}` is cited "
                    f"in the docs but exists as a string literal "
                    f"nowhere under {PACKAGE_DIR}/ — stale site "
                    "name?")
    return errors


def check(repo: str) -> List[str]:
    artifact_path = os.path.join(repo, ARTIFACT)
    with open(artifact_path) as f:
        detail = json.load(f)
    numbers = measured_numbers(detail)
    errors = []
    for doc in DOC_FILES:
        path = os.path.join(repo, doc)
        if not os.path.exists(path):
            continue
        for line_no, line, claim, nd in find_claims(path):
            if not claim_matches(claim, nd, numbers):
                errors.append(
                    f"{doc}:{line_no}: claim '{claim}x' has no "
                    f"measured counterpart in {ARTIFACT} "
                    f"(line: {line.strip()[:100]})")
    errors.extend(check_metric_names(repo))
    errors.extend(check_site_names(repo))
    return errors


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--repo", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    args = ap.parse_args(argv)
    errors = check(args.repo)
    if errors:
        print(f"{len(errors)} unmeasured perf claim(s):",
              file=sys.stderr)
        for e in errors:
            print("  " + e, file=sys.stderr)
        return 1
    print("perf claims OK: every N.Nx multiplier in "
          f"{'/'.join(DOC_FILES)} is backed by {ARTIFACT}, and every "
          "cited metric name exists in the code")
    return 0


if __name__ == "__main__":
    sys.exit(main())
