#!/usr/bin/env python
"""Lint: every "N.Nx" perf claim in the docs must be measured.

Two rounds in a row shipped prose speedups ("4.1x over exact masked
attention") whose numbers no bench artifact ever recorded — the
round-5 verdict's central complaint. This lint makes that impossible
going forward: every ``N.Nx`` / ``N.N×`` multiplier claimed in
README.md or COMPONENTS.md must correspond to a number present in
(or derivable from) the committed ``BENCH_DETAIL.json``:

- the value of an explicit RATIO key in the artifact (any key whose
  name contains ``vs_`` — ``vs_baseline``, ``vs_production_kernel``,
  ``vs_exact_masked``, ``fused_vs_bounded``, ...), matched at the
  claim's own precision (a "3.3x" claim matches a measured 3.316; a
  "3.3x" claim against a measured 2.1 fails);
- ratios between two configs' ``value`` fields sharing BOTH a unit
  and a metric family (the metric's first word — the "bf16 ResNet50
  is 1.44x the f32 ResNet50" class of claim).

Matching is deliberately NOT "any number anywhere in the artifact":
with hundreds of raw values and cross-config ratios, most fabricated
multipliers would collide with something by accident and the lint
would guarantee nothing.

Lines containing the word "target" are exempt — a declared goal
("BASELINE target: >= 0.70x of flax") is not a measurement claim.

Run: ``python tools/check_perf_claims.py [--repo DIR]``; exit 0 =
clean. Wired into the test tier via tests/test_observability.py.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import re
import sys
from typing import List, Tuple

DOC_FILES = ["README.md", "COMPONENTS.md"]
ARTIFACT = "BENCH_DETAIL.json"

# an N.Nx multiplier claim: requires a decimal point (plain "2x256"
# tensor shapes and "8x" core counts are not perf claims in this
# repo's docs; the measured-claim convention is one decimal or more)
CLAIM_RE = re.compile(r"(\d+\.\d+)\s*[x×]")


def _collect_ratio_keys(obj, out: List[float]) -> None:
    if isinstance(obj, dict):
        for k, v in obj.items():
            if "vs_" in str(k) and isinstance(v, (int, float)) \
                    and not isinstance(v, bool):
                out.append(float(v))
            else:
                _collect_ratio_keys(v, out)
    elif isinstance(obj, list):
        for v in obj:
            _collect_ratio_keys(v, out)


def measured_numbers(detail: dict) -> List[float]:
    """Legitimate multiplier sources only: explicit ``*vs_*`` ratio
    keys anywhere in the artifact, plus cross-config ``value`` ratios
    within one (unit, metric-family) pair — NOT every raw number."""
    out: List[float] = []
    _collect_ratio_keys(detail, out)
    configs = detail.get("configs", [])
    by_family = {}
    for c in configs:
        if isinstance(c.get("value"), (int, float)) and c.get("unit"):
            family = (c["unit"],
                      str(c.get("metric", "")).split(" ")[0])
            by_family.setdefault(family, []).append(float(c["value"]))
    for vals in by_family.values():
        for a, b in itertools.permutations(vals, 2):
            if b:
                out.append(a / b)
    return out


def claim_matches(claim: float, ndecimals: int,
                  numbers: List[float]) -> bool:
    tol = 10.0 ** (-ndecimals)
    return any(abs(n - claim) <= tol for n in numbers)


def find_claims(path: str) -> List[Tuple[int, str, float, int]]:
    """(line_no, line, claim_value, n_decimals) for each N.Nx."""
    claims = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            if "target" in line.lower():
                continue
            for m in CLAIM_RE.finditer(line):
                txt = m.group(1)
                claims.append((i, line.rstrip(), float(txt),
                               len(txt.split(".")[1])))
    return claims


def check(repo: str) -> List[str]:
    artifact_path = os.path.join(repo, ARTIFACT)
    with open(artifact_path) as f:
        detail = json.load(f)
    numbers = measured_numbers(detail)
    errors = []
    for doc in DOC_FILES:
        path = os.path.join(repo, doc)
        if not os.path.exists(path):
            continue
        for line_no, line, claim, nd in find_claims(path):
            if not claim_matches(claim, nd, numbers):
                errors.append(
                    f"{doc}:{line_no}: claim '{claim}x' has no "
                    f"measured counterpart in {ARTIFACT} "
                    f"(line: {line.strip()[:100]})")
    return errors


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--repo", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    args = ap.parse_args(argv)
    errors = check(args.repo)
    if errors:
        print(f"{len(errors)} unmeasured perf claim(s):",
              file=sys.stderr)
        for e in errors:
            print("  " + e, file=sys.stderr)
        return 1
    print("perf claims OK: every N.Nx multiplier in "
          f"{'/'.join(DOC_FILES)} is backed by {ARTIFACT}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
