#!/usr/bin/env python
"""DEPRECATED shim — this lint is now graftlint rule **GL005**
(``tools/graftlint/rules/gl005_literal_drift.py``).

Everything this script checked (unmeasured ``N.Nx`` doc perf claims,
stale metric names, stale chaos-site names) runs as part of
``python -m tools.graftlint`` and the ``pytest -m lint`` tier. The
module-level API (``check``, ``check_metric_names``,
``check_site_names``, ``measured_numbers``, ``claim_matches``,
``find_claims``) and the CLI (``python tools/check_perf_claims.py
[--repo DIR]``) are preserved verbatim for existing callers; new
callers should import from the GL005 module or run graftlint.
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.graftlint.rules.gl005_literal_drift import (  # noqa: E402,F401
    ARTIFACT, CLAIM_RE, DOC_FILES, METRIC_SUFFIXES,
    check, check_metric_names, check_site_names, claim_matches,
    find_claims, find_doc_metric_names, find_doc_site_names,
    measured_numbers, registered_metric_patterns,
    registered_site_literals)
from tools.graftlint.core import PACKAGE_DIR  # noqa: E402,F401


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--repo", default=_REPO)
    args = ap.parse_args(argv)
    print("note: check_perf_claims.py is deprecated; this is "
          "graftlint rule GL005 (python -m tools.graftlint)",
          file=sys.stderr)
    errors = check(args.repo)
    if errors:
        print(f"{len(errors)} unmeasured perf claim(s):",
              file=sys.stderr)
        for e in errors:
            print("  " + e, file=sys.stderr)
        return 1
    print("perf claims OK: every N.Nx multiplier in "
          f"{'/'.join(DOC_FILES)} is backed by {ARTIFACT}, and every "
          "cited metric name exists in the code")
    return 0


if __name__ == "__main__":
    sys.exit(main())
