#!/usr/bin/env python
"""trace_report — tail-latency attribution from traces, offline or live.

Three modes:

``python tools/trace_report.py FILE [FILE ...] [--trace ID] [--top N]``
    Each FILE is span data: a tracer JSONL dump (``Tracer.write_jsonl``
    / ``enable(jsonl_path=...)``), a Chrome trace-event JSON
    (``export_chrome_trace`` / a flight-recorder bundle's
    ``trace.json``), or a flight-recorder ``events.jsonl``. Multiple
    files are MERGED by trace id before rendering (deduped on span
    id), so a router dump and a replica dump view as one
    cross-process tree. Spans are grouped by trace id; the report
    shows per-phase p50/p95/p99 across traces, the dominant phase,
    and (``--trace`` or ``--top``) rendered span trees for the
    slowest requests.

``python tools/trace_report.py --url http://HOST:PORT [--top N]``
    Ask a live ModelServer: prints ``/debug/requests``'s
    latency-attribution report, in-flight requests, and recent slow
    traces.

``python tools/trace_report.py --collector http://HOST:PORT``
    Ask a live fleet collector: spans stitched across every fleet
    member (router root, replica subtrees), already on one wall-clock
    axis. ``--trace ID`` renders one stitched tree; without it the
    most recent traces are reported.

Exit codes: 0 ok, 2 usage / unreadable input.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

__all__ = ["load_spans", "merge_spans", "group_traces",
           "phase_percentiles", "render_trace", "report_text",
           "collector_spans", "main"]

# span names that are request phases (contiguous segments of one
# request); everything else in a trace renders but does not enter the
# phase table
PHASE_ORDER = ["admission", "queue_wait", "batch_form", "prefill",
               "device_step", "decode", "respond", "finalize"]


def load_spans(path: str) -> List[dict]:
    """Normalize any supported file into a span-dict list:
    ``{name, trace_id?, span_id?, parent_id?, ts_us, dur_us,
    args?, unclosed?}``."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    # a Chrome trace is ONE JSON document; JSONL fails that parse.
    # Require the traceEvents key before taking this branch — a
    # single-line JSONL dump also parses as one dict and must fall
    # through to the per-line path, not vanish into an empty report
    data = None
    try:
        data = json.loads(text)
    except ValueError:
        pass
    if isinstance(data, dict) and "traceEvents" in data:
        events = data.get("traceEvents", [])
        out = []
        for ev in events:
            if ev.get("ph") not in (None, "X"):
                continue
            args = ev.get("args") or {}
            out.append({
                "name": ev.get("name"),
                "ts_us": float(ev.get("ts", 0.0)),
                "dur_us": float(ev.get("dur", 0.0)),
                "trace_id": args.get("trace_id"),
                "span_id": args.get("span_id"),
                "parent_id": args.get("parent_id"),
                "args": args})
        return out
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            ev = json.loads(line)
        except ValueError:
            # a crash-truncated final line is exactly this tool's
            # post-mortem input — keep the readable spans
            continue
        if ev.get("kind") == "span_open" or ev.get("ph") == "open" \
                or ev.get("unclosed"):
            ev = dict(ev, unclosed=True, dur_us=0.0)
        elif ev.get("kind") not in (None, "span"):
            continue              # non-span flight-recorder events
        if "ts_us" not in ev or "name" not in ev:
            continue
        out.append(ev)
    return out


def merge_spans(span_lists: List[List[dict]]) -> List[dict]:
    """Concatenate span lists from several dumps, deduping on
    (trace id, span id) — the same span exported by two members (or
    the same file given twice) must not double a phase's weight.
    Spans without ids always pass through."""
    out: List[dict] = []
    seen = set()
    for spans in span_lists:
        for s in spans:
            tid, sid = s.get("trace_id"), s.get("span_id")
            if tid and sid:
                if (tid, sid) in seen:
                    continue
                seen.add((tid, sid))
            out.append(s)
    return out


def group_traces(spans: List[dict]) -> Dict[str, List[dict]]:
    """trace id -> its spans, time-ordered; id-less spans are
    dropped (they are fit-loop spans, not request spans)."""
    traces: Dict[str, List[dict]] = {}
    for s in spans:
        tid = s.get("trace_id")
        if tid:
            traces.setdefault(tid, []).append(s)
    for tid in traces:
        traces[tid].sort(key=lambda s: s.get("ts_us", 0.0))
    return traces


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def phase_percentiles(traces: Dict[str, List[dict]]) -> dict:
    """Across traces: per-phase duration percentiles (ms) and the
    whole-request percentiles, plus the dominant phase."""
    per_phase: Dict[str, List[float]] = {}
    wholes: List[float] = []
    for spans in traces.values():
        for s in spans:
            name = s.get("name")
            dur_ms = float(s.get("dur_us", 0.0)) / 1e3
            if name == "request":
                wholes.append(dur_ms)
            elif name in PHASE_ORDER:
                per_phase.setdefault(name, []).append(dur_ms)
    report = {"traces": len(traces), "phases_ms": {},
              "whole_ms": {}}
    wholes.sort()
    for q, p in (("p50", .5), ("p95", .95), ("p99", .99)):
        report["whole_ms"][q] = round(_percentile(wholes, p), 3)
    for name, vals in per_phase.items():
        vals.sort()
        report["phases_ms"][name] = {
            q: round(_percentile(vals, p), 3)
            for q, p in (("p50", .5), ("p95", .95), ("p99", .99))}
    if report["phases_ms"]:
        report["dominant_phase"] = {
            q: max(report["phases_ms"],
                   key=lambda n: report["phases_ms"][n][q])
            for q in ("p50", "p99")}
    return report


def render_trace(trace_id: str, spans: List[dict]) -> str:
    """One trace's span tree, children indented under their parent."""
    by_parent: Dict[Optional[str], List[dict]] = {}
    ids = {s.get("span_id") for s in spans if s.get("span_id")}
    for s in spans:
        parent = s.get("parent_id")
        if parent not in ids:
            parent = None          # root (or parent from another hop)
        by_parent.setdefault(parent, []).append(s)
    lines = [f"trace {trace_id}"]

    def walk(parent: Optional[str], depth: int) -> None:
        for s in sorted(by_parent.get(parent, []),
                        key=lambda s: s.get("ts_us", 0.0)):
            mark = "  " * depth + ("└─ " if depth else "")
            dur = float(s.get("dur_us", 0.0)) / 1e3
            extra = ""
            args = s.get("args") or {}
            if s.get("unclosed"):
                extra = "  [UNCLOSED]"
            elif args.get("error") or "error" in s:
                extra = f"  error={args.get('error') or s.get('error')}"
            if s.get("replica"):
                # collector-stitched spans carry their source member
                extra += f"  @{s['replica']}"
            lines.append(f"{mark}{s.get('name'):<12} "
                         f"{dur:10.3f} ms{extra}")
            sid = s.get("span_id")
            if sid:
                walk(sid, depth + 1)

    walk(None, 0)
    return "\n".join(lines)


def report_text(spans: List[dict], top: int = 3,
                only_trace: Optional[str] = None) -> str:
    traces = group_traces(spans)
    out: List[str] = []
    if only_trace is not None:
        matches = {t: s for t, s in traces.items()
                   if t.startswith(only_trace)}
        if not matches:
            return f"no trace matching {only_trace!r} " \
                   f"({len(traces)} trace(s) in file)"
        for tid, s in matches.items():
            out.append(render_trace(tid, s))
        return "\n\n".join(out)
    rep = phase_percentiles(traces)
    out.append(f"{rep['traces']} trace(s)")
    if rep["whole_ms"]:
        out.append("whole-request ms: " + "  ".join(
            f"{q}={v}" for q, v in rep["whole_ms"].items()))
    if rep["phases_ms"]:
        out.append(f"{'phase':<12} {'p50':>10} {'p95':>10} "
                   f"{'p99':>10}")
        for name in PHASE_ORDER:
            if name in rep["phases_ms"]:
                p = rep["phases_ms"][name]
                out.append(f"{name:<12} {p['p50']:>10.3f} "
                           f"{p['p95']:>10.3f} {p['p99']:>10.3f}")
        out.append("dominant phase: "
                   f"p50={rep['dominant_phase']['p50']} "
                   f"p99={rep['dominant_phase']['p99']}")
    # slowest requests, rendered
    def total(spans):
        return max((s.get("dur_us", 0.0) for s in spans
                    if s.get("name") == "request"), default=0.0)
    slowest = sorted(traces.items(), key=lambda kv: -total(kv[1]))
    for tid, s in slowest[:top]:
        out.append("")
        out.append(render_trace(tid, s))
    return "\n".join(out)


def report_url(base: str, top: int) -> str:
    import urllib.request
    base = base.rstrip("/")
    with urllib.request.urlopen(base + "/debug/requests") as r:
        dbg = json.load(r)
    out = [f"server {base}",
           f"in flight: {dbg.get('in_flight_count', 0)}"]
    for e in dbg.get("in_flight", []):
        out.append(f"  {e.get('trace_id')} {e.get('route')} "
                   f"phase={e.get('phase')} "
                   f"age={e.get('age_ms', 0):.1f}ms")
    att = dbg.get("latency_attribution", {})
    for ep, rep in att.items():
        out.append(f"\nendpoint {ep} ({rep.get('count', 0)} "
                   "request(s))")
        whole = rep.get("whole_ms")
        if whole:
            out.append("  whole ms: " + "  ".join(
                f"{q}={v}" for q, v in whole.items()))
        for name, p in rep.get("phases_ms", {}).items():
            out.append(f"  {name:<12} p50={p['p50']:>9.3f} "
                       f"p95={p['p95']:>9.3f} p99={p['p99']:>9.3f}")
        dom = rep.get("dominant_phase")
        if dom:
            out.append(f"  dominant: p50={dom['p50']} "
                       f"p99={dom['p99']}")
        ratio = rep.get("phase_sum_over_total")
        if ratio is not None:
            out.append(f"  phase-sum / whole: {ratio}")
    slow = dbg.get("recent", [])
    slow = [e for e in slow if e.get("slow")][-top:]
    if slow:
        out.append("\nrecent slow:")
        for e in slow:
            out.append(f"  {e.get('trace_id')} {e.get('route')} "
                       f"{e.get('duration_ms')}ms "
                       f"status={e.get('status')} "
                       f"phases={e.get('phases_ms')}")
    return "\n".join(out)


def collector_spans(base: str, trace: Optional[str] = None,
                    limit: int = 20) -> List[dict]:
    """Spans from a live fleet collector: one stitched trace
    (``trace`` id prefix) or the ``limit`` most recent traces."""
    import urllib.request
    base = base.rstrip("/")
    if trace is not None:
        with urllib.request.urlopen(
                f"{base}/debug/trace?trace_id={trace}") as r:
            return json.load(r).get("spans", [])
    with urllib.request.urlopen(
            f"{base}/traces?limit={limit}") as r:
        recent = json.load(r).get("traces", [])
    out: List[dict] = []
    for e in recent:
        tid = e.get("trace_id")
        if not tid:
            continue
        with urllib.request.urlopen(
                f"{base}/debug/trace?trace_id={tid}") as r:
            out.extend(json.load(r).get("spans", []))
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="trace_report",
        description="tail-latency attribution from span data, a "
                    "live ModelServer, or a fleet collector")
    p.add_argument("file", nargs="*", default=[],
                   help="span JSONL / Chrome trace / flight-recorder "
                        "events.jsonl (several files merge by trace "
                        "id)")
    p.add_argument("--url", default=None,
                   help="live server base URL (uses /debug/requests)")
    p.add_argument("--collector", default=None, metavar="URL",
                   help="live fleet collector base URL (stitched "
                        "cross-process traces)")
    p.add_argument("--trace", default=None, metavar="ID",
                   help="render only the trace(s) whose id starts "
                        "with ID")
    p.add_argument("--top", type=int, default=3,
                   help="how many slowest traces to render (file "
                        "mode) / slow requests to list (url mode)")
    args = p.parse_args(argv)
    sources = sum((bool(args.file), args.url is not None,
                   args.collector is not None))
    if sources != 1:
        p.print_usage(sys.stderr)
        print("trace_report: give exactly one of FILE(s), --url, "
              "or --collector", file=sys.stderr)
        return 2
    try:
        if args.url:
            print(report_url(args.url, args.top))
        elif args.collector:
            spans = collector_spans(args.collector,
                                    trace=args.trace,
                                    limit=max(args.top, 20))
            if args.trace and not spans:
                print(f"no trace matching {args.trace!r} on "
                      f"{args.collector}", file=sys.stderr)
                return 2
            print(report_text(spans, top=args.top,
                              only_trace=args.trace))
        else:
            spans = merge_spans([load_spans(f) for f in args.file])
            print(report_text(spans, top=args.top,
                              only_trace=args.trace))
    except OSError as e:
        print(f"trace_report: {e}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
