// Native data-loading runtime.
//
// The TPU-native equivalent of the reference's native ETL machinery
// (libnd4j-backed DataVec record readers + the device-affine
// MagicQueue, deeplearning4j-core parallelism/MagicQueue.java): a
// multi-threaded CSV/float parser feeding a bounded producer/consumer
// ring of ready-to-device batches. Python binds via ctypes
// (deeplearning4j_tpu/data/native_loader.py); each next() hands the
// consumer a fully assembled (features, one-hot labels) pair that goes
// straight into jax.device_put, keeping host ETL off the critical path
// the same way AsyncDataSetIterator's prefetch thread does — but with
// parsing itself parallel and allocation-free after warmup.
//
// C ABI only (no C++ symbols exported) so ctypes stays trivial.

#include <algorithm>
#include <atomic>
#include <cctype>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <filesystem>
#include <fstream>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#ifndef DL4J_NO_PNG
#include <png.h>
#endif

namespace {

struct Batch {
  std::vector<float> features;
  std::vector<float> labels;
  int n;  // rows actually filled (last batch may be short)
};

struct Loader {
  // config
  std::string path;
  int batch_size;
  int n_features;
  int label_index;   // -1: no labels
  int n_classes;     // 0: regression (1 label col)
  int queue_capacity;

  // state
  std::vector<std::string> lines;
  std::atomic<size_t> next_line{0};
  std::queue<Batch*> ready;
  std::mutex mu;
  std::condition_variable cv_ready, cv_space;
  std::vector<std::thread> workers;
  std::atomic<int> active_workers{0};
  std::atomic<int64_t> skipped_rows{0};
  bool stopped = false;

  ~Loader() { stop(); }

  void stop() {
    {
      std::lock_guard<std::mutex> lock(mu);
      stopped = true;
    }
    cv_space.notify_all();
    cv_ready.notify_all();
    for (auto& t : workers)
      if (t.joinable()) t.join();
    workers.clear();
    std::lock_guard<std::mutex> lock(mu);
    while (!ready.empty()) {
      delete ready.front();
      ready.pop();
    }
  }

  bool load_lines() {
    std::ifstream f(path);
    if (!f.is_open()) return false;
    std::string line;
    lines.clear();
    while (std::getline(f, line)) {
      if (!line.empty()) lines.push_back(line);
    }
    return true;
  }

  // parse one CSV line into the row-th slot of batch
  bool parse_line(const std::string& line, Batch* b, int row) {
    const char* p = line.c_str();
    char* end;
    int col = 0, feat_i = 0;
    bool saw_label = false;
    float label_val = 0.0f;
    float* feat_row = b->features.data() + (size_t)row * n_features;
    while (*p) {
      float v = strtof(p, &end);
      if (end == p) break;
      if (col == label_index) {
        label_val = v;
        saw_label = true;
      } else {
        if (feat_i >= n_features) return false;
        feat_row[feat_i++] = v;
      }
      ++col;
      p = end;
      while (*p == ',' || *p == ' ' || *p == '\t') ++p;
    }
    if (feat_i != n_features) return false;
    if (label_index >= 0 && !saw_label) return false;  // short row:
      // without this a row missing its label column would silently
      // train as class 0
    if (label_index >= 0) {
      if (n_classes > 0) {
        float* lab_row = b->labels.data() + (size_t)row * n_classes;
        std::memset(lab_row, 0, sizeof(float) * n_classes);
        int cls = (int)label_val;
        if (cls < 0 || cls >= n_classes) return false;
        lab_row[cls] = 1.0f;
      } else {
        b->labels[row] = label_val;
      }
    }
    return true;
  }

  void worker() {
    const int lab_width = label_index < 0 ? 0
                          : (n_classes > 0 ? n_classes : 1);
    for (;;) {
      size_t start = next_line.fetch_add((size_t)batch_size);
      if (start >= lines.size()) break;
      size_t end_i = std::min(start + (size_t)batch_size, lines.size());
      Batch* b = new Batch();
      b->features.resize((size_t)batch_size * n_features, 0.0f);
      if (lab_width) b->labels.resize((size_t)batch_size * lab_width, 0.0f);
      int row = 0;
      for (size_t i = start; i < end_i; ++i) {
        if (parse_line(lines[i], b, row)) ++row;
        else skipped_rows.fetch_add(1);
      }
      b->n = row;
      if (row == 0) {
        // an all-bad batch must not reach the queue: next() treats
        // n == 0 as end-of-data, which would silently drop every
        // remaining batch (and turn a misconfigured n_features into
        // a no-op instead of an error)
        delete b;
        continue;
      }
      std::unique_lock<std::mutex> lock(mu);
      cv_space.wait(lock, [&] {
        return stopped || (int)ready.size() < queue_capacity;
      });
      if (stopped) {
        delete b;
        break;
      }
      ready.push(b);
      cv_ready.notify_one();
    }
    if (active_workers.fetch_sub(1) == 1) cv_ready.notify_all();
  }

  void start(int n_threads) {
    active_workers = n_threads;
    for (int i = 0; i < n_threads; ++i)
      workers.emplace_back([this] { worker(); });
  }

  // returns rows in batch, 0 when exhausted, -1 on stopped
  int next(float* feat_out, float* lab_out) {
    Batch* b = nullptr;
    {
      std::unique_lock<std::mutex> lock(mu);
      cv_ready.wait(lock, [&] {
        return stopped || !ready.empty() || active_workers.load() == 0;
      });
      if (stopped) return -1;
      if (ready.empty()) return 0;  // workers done, queue drained
      b = ready.front();
      ready.pop();
      cv_space.notify_one();
    }
    std::memcpy(feat_out, b->features.data(),
                b->features.size() * sizeof(float));
    if (lab_out && !b->labels.empty())
      std::memcpy(lab_out, b->labels.data(),
                  b->labels.size() * sizeof(float));
    int n = b->n;
    delete b;
    return n;
  }
};

// ---------------------------------------------------------------------------
// native image ETL: directory-per-label PNG tree -> (B,H,W,C) float
// batches + one-hot labels, decoded by a worker pool (libpng). The
// DataVec ImageRecordReader path (reference
// deeplearning4j-core/.../RecordReaderDataSetIterator.java:52 over
// datavec-data-image) — justified by measurement: single-thread PIL
// decodes a 224x224 PNG in ~1.4 ms => 174 ms per batch-128, twice
// the ~88 ms TPU ResNet50 step; the native pool decodes in parallel
// outside the GIL and stays ahead of the device.

#ifndef DL4J_NO_PNG
// Decode a PNG into tightly packed 8-bit gray or RGB rows.
bool read_png(const char* path, int channels,
              std::vector<unsigned char>& out, unsigned* w,
              unsigned* h) {
  png_image image;
  std::memset(&image, 0, sizeof image);
  image.version = PNG_IMAGE_VERSION;
  if (!png_image_begin_read_from_file(&image, path)) return false;
  image.format = channels == 1 ? PNG_FORMAT_GRAY : PNG_FORMAT_RGB;
  out.resize(PNG_IMAGE_SIZE(image));
  if (!png_image_finish_read(&image, nullptr, out.data(), 0, nullptr)) {
    png_image_free(&image);
    return false;
  }
  *w = image.width;
  *h = image.height;
  return true;
}
#endif

struct ImageLoader {
  int batch_size, H, W, C, queue_capacity;
  std::vector<std::pair<std::string, int>> items;  // path, label idx
  std::vector<std::string> classes;
  std::atomic<size_t> next_item{0};
  std::queue<Batch*> ready;
  std::mutex mu;
  std::condition_variable cv_ready, cv_space;
  std::vector<std::thread> workers;
  std::atomic<int> active_workers{0};
  std::atomic<int64_t> skipped{0};
  bool stopped = false;

  ~ImageLoader() { stop(); }

  void stop() {
    {
      std::lock_guard<std::mutex> lock(mu);
      stopped = true;
    }
    cv_space.notify_all();
    cv_ready.notify_all();
    for (auto& t : workers)
      if (t.joinable()) t.join();
    workers.clear();
    std::lock_guard<std::mutex> lock(mu);
    while (!ready.empty()) {
      delete ready.front();
      ready.pop();
    }
  }

  bool scan(const std::string& root) {
    namespace fs = std::filesystem;
    std::error_code ec;
    if (!fs::is_directory(root, ec)) return false;
    for (auto& d : fs::directory_iterator(root, ec)) {
      if (d.is_directory()) classes.push_back(d.path().filename());
    }
    std::sort(classes.begin(), classes.end());
    for (size_t li = 0; li < classes.size(); ++li) {
      std::vector<std::string> files;
      for (auto& f :
           fs::directory_iterator(fs::path(root) / classes[li], ec)) {
        std::string ext = f.path().extension();
        std::transform(ext.begin(), ext.end(), ext.begin(), ::tolower);
        if (ext == ".png") files.push_back(f.path());
      }
      std::sort(files.begin(), files.end());
      for (auto& f : files) items.emplace_back(f, (int)li);
    }
    return !items.empty();
  }

  // bilinear resize (src 8-bit HxWxC) into the row-th slot as float
  void resize_into(const unsigned char* src, unsigned sw, unsigned sh,
                   Batch* b, int row) {
    float* dst = b->features.data() + (size_t)row * H * W * C;
    if ((int)sw == W && (int)sh == H) {
      const size_t n = (size_t)H * W * C;
      for (size_t i = 0; i < n; ++i) dst[i] = (float)src[i];
      return;
    }
    const float sx = (float)sw / W, sy = (float)sh / H;
    for (int y = 0; y < H; ++y) {
      float fy = (y + 0.5f) * sy - 0.5f;
      int y0 = (int)fy;
      y0 = std::max(0, std::min((int)sh - 1, y0));
      int y1 = std::min((int)sh - 1, y0 + 1);
      float wy = fy - y0;
      if (wy < 0) wy = 0;
      for (int x = 0; x < W; ++x) {
        float fx = (x + 0.5f) * sx - 0.5f;
        int x0 = (int)fx;
        x0 = std::max(0, std::min((int)sw - 1, x0));
        int x1 = std::min((int)sw - 1, x0 + 1);
        float wx = fx - x0;
        if (wx < 0) wx = 0;
        for (int c = 0; c < C; ++c) {
          float v00 = src[((size_t)y0 * sw + x0) * C + c];
          float v01 = src[((size_t)y0 * sw + x1) * C + c];
          float v10 = src[((size_t)y1 * sw + x0) * C + c];
          float v11 = src[((size_t)y1 * sw + x1) * C + c];
          dst[(((size_t)y * W) + x) * C + c] =
              v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
              v10 * wy * (1 - wx) + v11 * wy * wx;
        }
      }
    }
  }

  // One coordinator walks batches in order; each batch's decodes are
  // split across a scoped thread team (parallelism WITHIN the batch —
  // claiming whole batches per worker serializes the common
  // one-batch-in-flight training loop).
  void coordinator(int n_threads) {
#ifndef DL4J_NO_PNG
    const int n_classes = (int)classes.size();
    for (size_t start = 0; start < items.size();
         start += (size_t)batch_size) {
      {
        std::lock_guard<std::mutex> lock(mu);
        if (stopped) break;
      }
      size_t end_i = std::min(start + (size_t)batch_size, items.size());
      const int expected = (int)(end_i - start);
      Batch* b = new Batch();
      b->features.resize((size_t)batch_size * H * W * C, 0.0f);
      b->labels.resize((size_t)batch_size * n_classes, 0.0f);
      std::vector<char> ok((size_t)expected, 0);
      std::atomic<int> cursor{0};
      const int nt = std::max(1, std::min(n_threads, expected));
      std::vector<std::thread> team;
      for (int t = 0; t < nt; ++t) {
        team.emplace_back([&, this] {
          std::vector<unsigned char> buf;
          for (;;) {
            int j = cursor.fetch_add(1);
            if (j >= expected) break;
            unsigned sw = 0, sh = 0;
            if (!read_png(items[start + j].first.c_str(), C, buf, &sw,
                          &sh))
              continue;
            resize_into(buf.data(), sw, sh, b, j);
            b->labels[(size_t)j * n_classes + items[start + j].second] =
                1.0f;
            ok[(size_t)j] = 1;
          }
        });
      }
      for (auto& t : team) t.join();
      // compact failed rows out
      const size_t fstride = (size_t)H * W * C;
      int row = 0;
      for (int j = 0; j < expected; ++j) {
        if (!ok[(size_t)j]) {
          skipped.fetch_add(1);
          continue;
        }
        if (row != j) {
          std::memmove(b->features.data() + (size_t)row * fstride,
                       b->features.data() + (size_t)j * fstride,
                       fstride * sizeof(float));
          std::memmove(b->labels.data() + (size_t)row * n_classes,
                       b->labels.data() + (size_t)j * n_classes,
                       (size_t)n_classes * sizeof(float));
        }
        ++row;
      }
      b->n = row;
      if (row == 0) {
        delete b;
        continue;
      }
      std::unique_lock<std::mutex> lock(mu);
      cv_space.wait(lock, [&] {
        return stopped || (int)ready.size() < queue_capacity;
      });
      if (stopped) {
        delete b;
        break;
      }
      ready.push(b);
      cv_ready.notify_one();
    }
#endif
    if (active_workers.fetch_sub(1) == 1) cv_ready.notify_all();
  }

  void start(int n_threads) {
    active_workers = 1;
    workers.emplace_back([this, n_threads] { coordinator(n_threads); });
  }

  int next(float* feat_out, float* lab_out) {
    Batch* b = nullptr;
    {
      std::unique_lock<std::mutex> lock(mu);
      cv_ready.wait(lock, [&] {
        return stopped || !ready.empty() || active_workers.load() == 0;
      });
      if (stopped) return -1;
      if (ready.empty()) return 0;
      b = ready.front();
      ready.pop();
      cv_space.notify_one();
    }
    std::memcpy(feat_out, b->features.data(),
                b->features.size() * sizeof(float));
    if (lab_out && !b->labels.empty())
      std::memcpy(lab_out, b->labels.data(),
                  b->labels.size() * sizeof(float));
    int n = b->n;
    delete b;
    return n;
  }
};

// ---------------------------------------------------------------------------
// fast word counting for vocab construction (NLP VocabConstructor's
// hot loop; the reference parallelizes this across threads too)
struct WordCounts {
  std::vector<std::string> words;
  std::vector<int64_t> counts;
};

}  // namespace

extern "C" {

void* dl4j_csv_loader_create(const char* path, int batch_size,
                             int n_features, int label_index,
                             int n_classes, int n_threads,
                             int queue_capacity) {
  auto* l = new Loader();
  l->path = path;
  l->batch_size = batch_size;
  l->n_features = n_features;
  l->label_index = label_index;
  l->n_classes = n_classes;
  l->queue_capacity = queue_capacity > 0 ? queue_capacity : 4;
  if (!l->load_lines()) {
    delete l;
    return nullptr;
  }
  l->start(n_threads > 0 ? n_threads : 2);
  return l;
}

int64_t dl4j_loader_num_lines(void* handle) {
  return (int64_t) static_cast<Loader*>(handle)->lines.size();
}

// rows dropped by the parser so far (bad numeric fields, wrong column
// count, out-of-range labels); lets the Python side warn instead of
// silently training on a subset
int64_t dl4j_loader_skipped_rows(void* handle) {
  return static_cast<Loader*>(handle)->skipped_rows.load();
}

int dl4j_loader_next(void* handle, float* feat_out, float* lab_out) {
  return static_cast<Loader*>(handle)->next(feat_out, lab_out);
}

void dl4j_loader_destroy(void* handle) {
  delete static_cast<Loader*>(handle);
}

// Image-tree loader (PNG via libpng; 0/nullptr when built without it)
void* dl4j_image_loader_create(const char* root, int batch_size,
                               int height, int width, int channels,
                               int n_threads, int queue_capacity) {
#ifdef DL4J_NO_PNG
  (void)root; (void)batch_size; (void)height; (void)width;
  (void)channels; (void)n_threads; (void)queue_capacity;
  return nullptr;
#else
  auto* l = new ImageLoader();
  l->batch_size = batch_size;
  l->H = height;
  l->W = width;
  l->C = channels == 1 ? 1 : 3;
  l->queue_capacity = queue_capacity > 0 ? queue_capacity : 4;
  if (!l->scan(root)) {
    delete l;
    return nullptr;
  }
  l->start(n_threads > 0 ? n_threads : 4);
  return l;
#endif
}

int dl4j_image_loader_available() {
#ifdef DL4J_NO_PNG
  return 0;
#else
  return 1;
#endif
}

int64_t dl4j_image_loader_num_items(void* handle) {
  return (int64_t) static_cast<ImageLoader*>(handle)->items.size();
}

int dl4j_image_loader_num_classes(void* handle) {
  return (int)static_cast<ImageLoader*>(handle)->classes.size();
}

const char* dl4j_image_loader_class_name(void* handle, int i) {
  return static_cast<ImageLoader*>(handle)->classes[i].c_str();
}

int64_t dl4j_image_loader_skipped(void* handle) {
  return static_cast<ImageLoader*>(handle)->skipped.load();
}

int dl4j_image_loader_next(void* handle, float* feat_out,
                           float* lab_out) {
  return static_cast<ImageLoader*>(handle)->next(feat_out, lab_out);
}

void dl4j_image_loader_destroy(void* handle) {
  delete static_cast<ImageLoader*>(handle);
}

// Count whitespace-separated tokens in a text file using n_threads.
// Returns a handle; query with dl4j_counts_size/get, free with
// dl4j_counts_destroy. Tokens are lowercased; ASCII punctuation
// stripped from token edges (CommonPreprocessor-lite).
void* dl4j_count_words(const char* path, int n_threads) {
  std::ifstream f(path);
  if (!f.is_open()) return nullptr;
  std::string content((std::istreambuf_iterator<char>(f)),
                      std::istreambuf_iterator<char>());
  int nt = n_threads > 0 ? n_threads : 4;
  size_t chunk = content.size() / nt + 1;
  std::vector<std::unordered_map<std::string, int64_t>> partial(nt);
  std::vector<std::thread> threads;
  for (int t = 0; t < nt; ++t) {
    threads.emplace_back([&, t] {
      size_t start = t * chunk;
      size_t end = std::min(content.size(), start + chunk);
      if (start > 0) {  // skip partial token at chunk head
        while (start < end && !isspace((unsigned char)content[start]))
          ++start;
      }
      // include token spilling past chunk tail
      size_t hard_end = end;
      while (hard_end < content.size() &&
             !isspace((unsigned char)content[hard_end]))
        ++hard_end;
      std::string tok;
      auto flush = [&] {
        if (!tok.empty()) {
          partial[t][tok] += 1;
          tok.clear();
        }
      };
      for (size_t i = start; i < hard_end; ++i) {
        char c = content[i];
        if (isspace((unsigned char)c)) {
          flush();
        } else if (isalnum((unsigned char)c) || c == '\'' || c == '-' ||
                   (unsigned char)c >= 128) {
          tok.push_back((char)tolower((unsigned char)c));
        }
        // other punctuation: dropped
      }
      flush();
    });
  }
  for (auto& t : threads) t.join();
  auto* out = new WordCounts();
  std::unordered_map<std::string, int64_t> merged;
  for (auto& m : partial)
    for (auto& kv : m) merged[kv.first] += kv.second;
  out->words.reserve(merged.size());
  for (auto& kv : merged) {
    out->words.push_back(kv.first);
    out->counts.push_back(kv.second);
  }
  return out;
}

int64_t dl4j_counts_size(void* handle) {
  return (int64_t) static_cast<WordCounts*>(handle)->words.size();
}

const char* dl4j_counts_word(void* handle, int64_t i) {
  return static_cast<WordCounts*>(handle)->words[i].c_str();
}

int64_t dl4j_counts_count(void* handle, int64_t i) {
  return static_cast<WordCounts*>(handle)->counts[i];
}

void dl4j_counts_destroy(void* handle) {
  delete static_cast<WordCounts*>(handle);
}

}  // extern "C"
